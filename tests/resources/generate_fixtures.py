"""Generate the committed import-conformance fixtures.

Reference pattern: ``dl4j-test-resources`` — a directory of REAL model
files + golden input/output pairs, driven by a parameterized conformance
test (``TFGraphTestAllSameDiff``). This environment is zero-egress and
has no TensorFlow/Keras, so the fixtures are written here ONCE, with the
exact on-disk formats those writers produce:

- Keras ``.h5``: HDF5 with ``model_config``/``keras_version``/``backend``
  root attributes, ``model_weights`` with ``layer_names`` +
  ``top_level_model_weights`` bookkeeping attrs, per-layer
  ``weight_names`` attrs, and ``<layer>/<layer>/<weight>:0`` dataset
  paths — the Keras 2.x ``save_model`` layout, byte-stable across runs
  (fixed weights, no timestamps).
- TF ``.pb``: a frozen GraphDef serialized through the wire-compatible
  vendored protos — protobuf wire bytes are identical to what TF's own
  writer emits for the same message content (same field numbers, same
  serialization order).

Golden outputs are computed by INDEPENDENT numpy forward math at
generation time, never by the importer under test. Run this script only
to regenerate after a deliberate format change; the test suite consumes
the committed binaries.
"""

import json
import os
import sys

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.join(HERE, "conformance")
sys.path.insert(0, os.path.join(HERE, "..", ".."))


def _keras_h5(path, model_cfg, weights, layer_order):
    import h5py

    with h5py.File(path, "w", track_order=True) as f:
        f.attrs["model_config"] = json.dumps(model_cfg)
        f.attrs["keras_version"] = b"2.10.0"
        f.attrs["backend"] = b"tensorflow"
        mw = f.create_group("model_weights")
        mw.attrs["layer_names"] = [n.encode() for n in layer_order]
        mw.attrs["backend"] = b"tensorflow"
        mw.attrs["keras_version"] = b"2.10.0"
        for lname in layer_order:
            g = mw.create_group(lname)
            ws = weights.get(lname, {})
            names = []
            if ws:
                inner = g.create_group(lname)
                for wname, arr in ws.items():
                    inner.create_dataset(f"{wname}:0", data=arr)
                    names.append(f"{lname}/{wname}:0".encode())
            g.attrs["weight_names"] = names
        tl = f.create_group("top_level_model_weights")
        tl.attrs["weight_names"] = []


def _write(case, files):
    d = os.path.join(ROOT, case)
    os.makedirs(d, exist_ok=True)
    for name, data in files.items():
        p = os.path.join(d, name)
        if isinstance(data, np.ndarray):
            np.save(p, data)
        elif isinstance(data, (bytes, bytearray)):
            with open(p, "wb") as f:
                f.write(data)
        else:
            with open(p, "w") as f:
                json.dump(data, f, indent=1, sort_keys=True)


def gen_keras_mlp():
    rng = np.random.default_rng(1234)
    w1 = rng.normal(size=(4, 8)).astype(np.float32)
    b1 = rng.normal(size=(8,)).astype(np.float32)
    w2 = rng.normal(size=(8, 3)).astype(np.float32)
    b2 = rng.normal(size=(3,)).astype(np.float32)
    cfg = {"class_name": "Sequential",
           "config": {"name": "sequential", "layers": [
               {"class_name": "InputLayer", "config": {
                   "batch_input_shape": [None, 4], "dtype": "float32",
                   "sparse": False, "ragged": False,
                   "name": "dense_input"}},
               {"class_name": "Dense", "config": {
                   "name": "dense", "trainable": True, "dtype": "float32",
                   "units": 8, "activation": "tanh", "use_bias": True,
                   "batch_input_shape": [None, 4]}},
               {"class_name": "Dense", "config": {
                   "name": "dense_1", "trainable": True,
                   "dtype": "float32", "units": 3,
                   "activation": "softmax", "use_bias": True}}]}}
    x = rng.normal(size=(5, 4)).astype(np.float32)
    h = np.tanh(x @ w1 + b1)
    logits = h @ w2 + b2
    y = np.exp(logits) / np.exp(logits).sum(-1, keepdims=True)
    os.makedirs(os.path.join(ROOT, "keras_mlp"), exist_ok=True)
    _keras_h5(os.path.join(ROOT, "keras_mlp", "model.h5"), cfg,
              {"dense": {"kernel": w1, "bias": b1},
               "dense_1": {"kernel": w2, "bias": b2}},
              ["dense_input", "dense", "dense_1"])
    _write("keras_mlp", {
        "input.npy": x, "expected.npy": y.astype(np.float32),
        "META.json": {"kind": "keras", "rtol": 1e-4, "atol": 1e-5,
                      "desc": "Sequential Dense(tanh)+Dense(softmax)"},
    })


def gen_keras_gru():
    rng = np.random.default_rng(77)
    u, fdim, t = 4, 3, 6
    kernel = rng.normal(size=(fdim, 3 * u)).astype(np.float32)
    rec = rng.normal(size=(u, 3 * u)).astype(np.float32)
    bias = rng.normal(size=(2, 3 * u)).astype(np.float32)
    w2 = rng.normal(size=(u, 2)).astype(np.float32)
    b2 = np.zeros(2, np.float32)
    cfg = {"class_name": "Sequential",
           "config": {"name": "sequential", "layers": [
               {"class_name": "GRU", "config": {
                   "name": "gru", "trainable": True, "dtype": "float32",
                   "units": u, "activation": "tanh",
                   "recurrent_activation": "sigmoid",
                   "return_sequences": True, "reset_after": True,
                   "go_backwards": False,
                   "batch_input_shape": [None, t, fdim]}},
               {"class_name": "Dense", "config": {
                   "name": "dense", "trainable": True, "dtype": "float32",
                   "units": 2, "activation": "softmax",
                   "use_bias": True}}]}}
    x = rng.normal(size=(2, t, fdim)).astype(np.float32)

    def sigmoid(z):
        return 1.0 / (1.0 + np.exp(-z))

    kz, kr, kh = np.split(kernel, 3, axis=1)
    rz, rr, rh = np.split(rec, 3, axis=1)
    bz, br, bh = np.split(bias[0], 3)
    rbz, rbr, rbh = np.split(bias[1], 3)
    hstate = np.zeros((2, u), np.float32)
    outs = []
    for ti in range(t):
        xt = x[:, ti]
        z = sigmoid(xt @ kz + bz + hstate @ rz + rbz)
        r = sigmoid(xt @ kr + br + hstate @ rr + rbr)
        hh = np.tanh(xt @ kh + bh + r * (hstate @ rh + rbh))
        hstate = z * hstate + (1 - z) * hh
        outs.append(hstate.copy())
    hs = np.stack(outs, 1)
    logits = hs @ w2 + b2
    y = np.exp(logits) / np.exp(logits).sum(-1, keepdims=True)
    os.makedirs(os.path.join(ROOT, "keras_gru"), exist_ok=True)
    _keras_h5(os.path.join(ROOT, "keras_gru", "model.h5"), cfg,
              {"gru": {"kernel": kernel, "recurrent_kernel": rec,
                       "bias": bias},
               "dense": {"kernel": w2, "bias": b2}},
              ["gru", "dense"])
    _write("keras_gru", {
        "input.npy": x, "expected.npy": y.astype(np.float32),
        "META.json": {"kind": "keras", "rtol": 1e-3, "atol": 1e-4,
                      "desc": "GRU(reset_after) + Dense(softmax)"},
    })


def gen_keras_bidirectional():
    import h5py

    rng = np.random.default_rng(31)
    u, fdim, t = 3, 2, 5
    mk = lambda *s: rng.normal(size=s).astype(np.float32)  # noqa: E731
    fk, fr, fb = mk(fdim, 4 * u), mk(u, 4 * u), mk(4 * u)
    bk, br, bb = mk(fdim, 4 * u), mk(u, 4 * u), mk(4 * u)
    w2, b2 = mk(2 * u, 2), np.zeros(2, np.float32)
    cfg = {"class_name": "Sequential",
           "config": {"name": "sequential", "layers": [
               {"class_name": "Bidirectional", "config": {
                   "name": "bidirectional", "trainable": True,
                   "dtype": "float32", "merge_mode": "concat",
                   "batch_input_shape": [None, t, fdim],
                   "layer": {"class_name": "LSTM", "config": {
                       "name": "lstm", "trainable": True,
                       "dtype": "float32", "units": u,
                       "activation": "tanh",
                       "recurrent_activation": "sigmoid",
                       "return_sequences": True,
                       "go_backwards": False}}}},
               {"class_name": "Dense", "config": {
                   "name": "dense", "trainable": True,
                   "dtype": "float32", "units": 2,
                   "activation": "softmax", "use_bias": True}}]}}
    d = os.path.join(ROOT, "keras_bidirectional")
    os.makedirs(d, exist_ok=True)
    with h5py.File(os.path.join(d, "model.h5"), "w", track_order=True) as f:
        f.attrs["model_config"] = json.dumps(cfg)
        f.attrs["keras_version"] = b"2.10.0"
        f.attrs["backend"] = b"tensorflow"
        mw = f.create_group("model_weights")
        mw.attrs["layer_names"] = [b"bidirectional", b"dense"]
        g = mw.create_group("bidirectional").create_group("bidirectional")
        names = []
        for sub, (kk, rr, bbias) in (("forward_lstm", (fk, fr, fb)),
                                     ("backward_lstm", (bk, br, bb))):
            gg = g.create_group(sub)
            cell = gg.create_group("lstm_cell")  # keras 2.10 nests the cell
            cell.create_dataset("kernel:0", data=kk)
            cell.create_dataset("recurrent_kernel:0", data=rr)
            cell.create_dataset("bias:0", data=bbias)
            names += [f"bidirectional/{sub}/lstm_cell/{w}:0".encode()
                      for w in ("kernel", "recurrent_kernel", "bias")]
        mw["bidirectional"].attrs["weight_names"] = names
        gd = mw.create_group("dense").create_group("dense")
        gd.create_dataset("kernel:0", data=w2)
        gd.create_dataset("bias:0", data=b2)
        mw["dense"].attrs["weight_names"] = [b"dense/kernel:0",
                                             b"dense/bias:0"]

    def sigmoid(z):
        return 1.0 / (1.0 + np.exp(-z))

    def np_lstm(x, kernel, rec, bias):
        ki, kf, kc, ko = np.split(kernel, 4, axis=1)
        ri, rf, rc, ro = np.split(rec, 4, axis=1)
        bi, bf, bc, bo = np.split(bias, 4)
        h = np.zeros((x.shape[0], u), np.float32)
        c = np.zeros((x.shape[0], u), np.float32)
        outs = []
        for ti in range(x.shape[1]):
            xt = x[:, ti]
            i = sigmoid(xt @ ki + h @ ri + bi)
            fgt = sigmoid(xt @ kf + h @ rf + bf)
            gg = np.tanh(xt @ kc + h @ rc + bc)
            o = sigmoid(xt @ ko + h @ ro + bo)
            c = fgt * c + i * gg
            h = o * np.tanh(c)
            outs.append(h.copy())
        return np.stack(outs, 1)

    x = rng.normal(size=(2, t, fdim)).astype(np.float32)
    hs = np.concatenate([np_lstm(x, fk, fr, fb),
                         np_lstm(x[:, ::-1], bk, br, bb)[:, ::-1]], -1)
    logits = hs @ w2 + b2
    y = np.exp(logits) / np.exp(logits).sum(-1, keepdims=True)
    _write("keras_bidirectional", {
        "input.npy": x, "expected.npy": y.astype(np.float32),
        "META.json": {"kind": "keras", "rtol": 1e-3, "atol": 1e-4,
                      "desc": "Bidirectional(LSTM, concat) with "
                              "forward_/backward_ lstm_cell nesting"},
    })


def gen_tf_mlp():
    from deeplearning4j_tpu.imports.protos import tf_graph_pb2 as pb

    rng = np.random.default_rng(55)
    w1 = rng.normal(size=(4, 6)).astype(np.float32)
    b1 = rng.normal(size=(6,)).astype(np.float32)
    w2 = rng.normal(size=(6, 3)).astype(np.float32)

    g = pb.GraphDef()
    n = g.node.add()
    n.name, n.op = "input", "Placeholder"
    n.attr["dtype"].type = pb.DT_FLOAT
    sh = n.attr["shape"].shape
    sh.dim.add().size = -1
    sh.dim.add().size = 4

    def const(name, arr):
        c = g.node.add()
        c.name, c.op = name, "Const"
        c.attr["dtype"].type = pb.DT_FLOAT
        tns = c.attr["value"].tensor
        tns.dtype = pb.DT_FLOAT
        for d in arr.shape:
            tns.tensor_shape.dim.add().size = d
        tns.tensor_content = arr.tobytes()

    def node(name, op, *ins, **attrs):
        m = g.node.add()
        m.name, m.op = name, op
        m.input.extend(ins)
        for k, v in attrs.items():
            if isinstance(v, bool):
                m.attr[k].b = v
        return m

    const("w1", w1)
    const("b1", b1)
    const("w2", w2)
    node("mm1", "MatMul", "input", "w1", transpose_a=False,
         transpose_b=False)
    node("h", "BiasAdd", "mm1", "b1")
    node("relu", "Relu", "h")
    node("logits", "MatMul", "relu", "w2", transpose_a=False,
         transpose_b=False)
    node("probs", "Softmax", "logits")

    x = rng.normal(size=(3, 4)).astype(np.float32)
    hidden = np.maximum(x @ w1 + b1, 0.0)
    logits = hidden @ w2
    y = np.exp(logits) / np.exp(logits).sum(-1, keepdims=True)
    _write("tf_mlp", {
        "graph.pb": g.SerializeToString(),
        "input.npy": x, "expected.npy": y.astype(np.float32),
        "META.json": {"kind": "tf", "input": "input", "output": "probs",
                      "rtol": 1e-4, "atol": 1e-5,
                      "desc": "frozen MLP GraphDef"},
    })


def gen_tf_while():
    from deeplearning4j_tpu.imports.protos import tf_graph_pb2 as pb

    g = pb.GraphDef()
    n = g.node.add()
    n.name, n.op = "x", "Placeholder"
    n.attr["dtype"].type = pb.DT_FLOAT
    n.attr["shape"].shape.dim.add().size = 4
    c = g.node.add()
    c.name, c.op = "i0", "Const"
    c.attr["dtype"].type = pb.DT_FLOAT
    c.attr["value"].tensor.dtype = pb.DT_FLOAT
    c.attr["value"].tensor.float_val.append(0.0)

    fc = g.library.function.add()
    fc.signature.name = "while_cond"
    for a in ("i", "x"):
        arg = fc.signature.input_arg.add()
        arg.name, arg.type = a, pb.DT_FLOAT
    oa = fc.signature.output_arg.add()
    oa.name, oa.type = "ok", pb.DT_FLOAT
    lim = fc.node_def.add()
    lim.name, lim.op = "lim", "Const"
    lim.attr["value"].tensor.dtype = pb.DT_FLOAT
    lim.attr["value"].tensor.float_val.append(4.0)
    lt = fc.node_def.add()
    lt.name, lt.op = "lt", "Less"
    lt.input.extend(["i", "lim"])
    fc.ret["ok"] = "lt:z:0"

    fb = g.library.function.add()
    fb.signature.name = "while_body"
    for a in ("i", "x"):
        arg = fb.signature.input_arg.add()
        arg.name, arg.type = a, pb.DT_FLOAT
    for o in ("io", "xo"):
        arg = fb.signature.output_arg.add()
        arg.name, arg.type = o, pb.DT_FLOAT
    one = fb.node_def.add()
    one.name, one.op = "one", "Const"
    one.attr["value"].tensor.dtype = pb.DT_FLOAT
    one.attr["value"].tensor.float_val.append(1.0)
    inc = fb.node_def.add()
    inc.name, inc.op = "inc", "AddV2"
    inc.input.extend(["i", "one"])
    sc = fb.node_def.add()
    sc.name, sc.op = "scale", "Const"
    sc.attr["value"].tensor.dtype = pb.DT_FLOAT
    sc.attr["value"].tensor.float_val.append(1.5)
    scl = fb.node_def.add()
    scl.name, scl.op = "half_more", "Mul"
    scl.input.extend(["x", "scale"])
    fb.ret["io"] = "inc:z:0"
    fb.ret["xo"] = "half_more:z:0"

    w = g.node.add()
    w.name, w.op = "loop", "StatelessWhile"
    w.input.extend(["i0", "x"])
    w.attr["cond"].func.name = "while_cond"
    w.attr["body"].func.name = "while_body"

    x = np.asarray([1.0, -2.0, 0.5, 4.0], np.float32)
    y = x * (1.5 ** 4)
    _write("tf_while", {
        "graph.pb": g.SerializeToString(),
        "input.npy": x, "expected.npy": y.astype(np.float32),
        "META.json": {"kind": "tf", "input": "x", "output": "loop:1",
                      "rtol": 1e-4, "atol": 1e-5,
                      "desc": "StatelessWhile (x*1.5, 4 iters) via "
                              "FunctionDefLibrary"},
    })


if __name__ == "__main__":
    os.makedirs(ROOT, exist_ok=True)
    gen_keras_mlp()
    gen_keras_gru()
    gen_keras_bidirectional()
    gen_tf_mlp()
    gen_tf_while()
    print("fixtures written under", ROOT)
