"""Iteration-level continuous batching for autoregressive decode
(``nn.decoding`` + ``parallel.generation``).

The invariants pinned here are the acceptance criteria of the decode
subsystem: greedy generation through the KV cache matches the full
no-cache forward exactly; continuous scheduling (token-granularity
join/leave, fused-K windows, bucket growth) NEVER changes any
sequence's tokens relative to the sequential one-request-at-a-time
reference; warmup makes mixed-length traffic zero-recompile; finished
sequences free their rows immediately; admission control (400/503/
deadline/breaker-shed) matches the serving batcher's semantics; and the
program linter's donation audit proves every decode/prefill executable
writes the KV cache in place.

All cache assertions read COUNTER DELTAS — the AOT executable cache and
the telemetry registry are process-global and shared across the session.
"""

import functools
import threading
import time

import numpy as np
import pytest

from deeplearning4j_tpu.nn.decoding import (
    TransformerDecoder,
    bucket_for,
    pow2_ladder,
)
from deeplearning4j_tpu.optimize import aot_cache
from deeplearning4j_tpu.parallel.batcher import (
    BadRequestError,
    DeadlineExpiredError,
    ServerOverloadedError,
)
from deeplearning4j_tpu.parallel.generation import (
    GenerationConfig,
    GenerationEngine,
)
from deeplearning4j_tpu.resilience.breaker import (
    CircuitBreaker,
    CircuitOpenError,
)
from deeplearning4j_tpu.resilience.faults import FaultPlan
from deeplearning4j_tpu.telemetry import REGISTRY
from deeplearning4j_tpu.zoo.graphs import TransformerEncoder

pytestmark = pytest.mark.decode

VOCAB = 32
MAX_LEN = 32
MAX_BATCH = 4
K = 2


@functools.lru_cache(maxsize=None)
def _decoder() -> TransformerDecoder:
    """One warmed decoder for the whole module (executables are shared
    through the process-global AOT cache anyway; warming once keeps the
    suite fast)."""
    m = TransformerEncoder(vocab_size=VOCAB, embed_dim=16, n_heads=2,
                           n_layers=2, max_len=MAX_LEN, causal=True,
                           lm_head=True, seed=7)
    dec = m.decoder(max_batch=MAX_BATCH, kv_bucket_min=16,
                    prompt_bucket_min=4)
    dec.warm_all(fused_steps=(1, K))
    return dec


def _engine(**over):
    cfg = dict(max_batch=MAX_BATCH, fused_steps=K, kv_bucket_min=16,
               prompt_bucket_min=4)
    cfg.update(over)
    return GenerationEngine(_decoder(), GenerationConfig(**cfg))


# --- bucket math -----------------------------------------------------------

def test_pow2_ladder_and_bucket_for():
    assert pow2_ladder(8, 64) == [8, 16, 32, 64]
    assert pow2_ladder(32, 48) == [32, 48]  # capped at (and including) hi
    assert pow2_ladder(64, 32) == [32]
    assert bucket_for(9, [8, 16, 32]) == 16
    assert bucket_for(16, [8, 16, 32]) == 16
    with pytest.raises(ValueError):
        bucket_for(33, [8, 16, 32])


# --- KV-cache math against the no-cache oracle -----------------------------

def test_greedy_generate_matches_full_forward_oracle():
    """The KV-cached prefill+decode path must produce exactly the tokens
    the full no-cache forward picks: grow the sequence one token at a
    time through ``net.output`` and argmax the last position."""
    dec = _decoder()
    prompt = [3, 9, 1, 14, 2]
    out = dec.generate(prompt, max_new=6)
    seq = list(prompt)
    ref = []
    for _ in range(6):
        y = np.asarray(dec.net.output(np.asarray([seq], np.int32)))
        ref.append(int(np.argmax(y[0, len(seq) - 1])))
        seq.append(ref[-1])
    assert out == ref


def test_fused_k1_vs_k4_token_identical():
    dec = _decoder()
    prompt = [5, 6, 7, 8, 2, 11]
    a = dec.generate(prompt, max_new=9, fused_steps=1)
    b = dec.generate(prompt, max_new=9, fused_steps=K)
    assert a == b


def test_generate_stops_at_eos():
    dec = _decoder()
    ref = dec.generate([4, 8, 15], max_new=8)
    eos = ref[2]
    out = dec.generate([4, 8, 15], max_new=8, eos_id=eos)
    assert out == ref[:ref.index(eos) + 1]
    assert out[-1] == eos


def test_temperature_sampling_deterministic_per_seed():
    dec = _decoder()
    a = dec.generate([1, 2, 3], max_new=8, temperature=0.9, seed=123)
    b = dec.generate([1, 2, 3], max_new=8, temperature=0.9, seed=123)
    assert a == b  # same seed replays the same per-request stream
    assert all(0 <= t < VOCAB for t in a)
    greedy = dec.generate([1, 2, 3], max_new=8)
    assert len(a) == len(greedy) == 8


def test_unsupported_graphs_rejected():
    """Graphs the decode path cannot serve faithfully refuse at
    construction: classifier heads (pooling), MoE FFNs (cross-row
    routing breaks the row-independence the bit-identity pin rests on),
    and non-causal attention."""
    with pytest.raises(ValueError, match="lm_head"):
        TransformerEncoder(vocab_size=16, causal=True).decoder()
    moe = TransformerEncoder(vocab_size=16, embed_dim=8, n_heads=2,
                             n_layers=1, max_len=16, causal=True,
                             lm_head=True, moe_experts=2)
    with pytest.raises(ValueError, match="MoELayer"):
        moe.decoder(max_batch=2)
    with pytest.raises(ValueError, match="causal"):
        TransformerEncoder(vocab_size=16, lm_head=True)


def test_request_validation():
    dec = _decoder()
    with pytest.raises(ValueError, match="at least one token"):
        dec.validate_request([], 4)
    with pytest.raises(ValueError, match="token ids"):
        dec.validate_request([VOCAB], 4)
    with pytest.raises(ValueError, match="exceeds max_len"):
        dec.validate_request([1] * 30, 8)


# --- continuous scheduling == sequential reference -------------------------

def test_continuous_engine_token_identical_to_sequential():
    """Five requests churn through four cache rows (join/leave mid-
    flight, mixed prompt/output lengths) and every sequence's greedy
    tokens equal the sequential one-at-a-time reference exactly."""
    dec = _decoder()
    prompts = [[3, 9, 1], [5, 6, 7, 8, 2, 11], [1], [14, 13, 12, 2],
               [9, 9, 2, 3, 4, 5, 6, 1]]
    mns = [6, 9, 4, 12, 5]
    refs = [dec.generate(p, mn) for p, mn in zip(prompts, mns)]
    with _engine() as eng:
        eng.warmup()
        reqs = [eng.submit(p, max_new_tokens=mn)
                for p, mn in zip(prompts, mns)]
        outs = [eng.result(r) for r in reqs]
    assert outs == refs


def test_sampled_engine_matches_sequential_reference():
    """Per-sequence PRNG keys make even temperature sampling immune to
    co-tenant churn: engine output equals the sequential reference for
    the same (seed, temperature)."""
    dec = _decoder()
    ref = dec.generate([2, 4, 6], max_new=7, temperature=0.8, seed=42)
    with _engine() as eng:
        out = eng.generate([2, 4, 6], max_new_tokens=7, temperature=0.8,
                           seed=42)
    assert out == ref


def test_late_join_completes_before_earlier_longer_sequence():
    """Token-granularity admission: a short request submitted AFTER a
    long one is already decoding joins the running batch at the next
    iteration and finishes first — no request-granularity drain wait."""
    dec = _decoder()
    long_ref = dec.generate([7, 3], max_new=24)
    short_ref = dec.generate([9, 9, 2], max_new=3)
    order = []
    with _engine() as eng:
        long_req = eng.submit([7, 3], max_new_tokens=24)
        # wait until the long request is genuinely mid-generation
        deadline = time.monotonic() + 5
        while len(long_req.out) < 4:
            assert time.monotonic() < deadline, "long request never started"
            time.sleep(0.002)
        short_req = eng.submit([9, 9, 2], max_new_tokens=3)

        def wait(tag, req):
            eng.result(req)
            order.append(tag)

        ts = [threading.Thread(target=wait, args=("long", long_req)),
              threading.Thread(target=wait, args=("short", short_req))]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=30)
        assert eng.result(short_req) == short_ref
        assert eng.result(long_req) == long_ref
    assert order[0] == "short", "late-joining short request should retire first"


def test_eos_retirement_frees_rows():
    dec = _decoder()
    ref = dec.generate([4, 8, 15], max_new=8)
    eos = ref[2]
    with _engine() as eng:
        before = eng.stats()
        out = eng.generate([4, 8, 15], max_new_tokens=8, eos_id=eos)
        after = eng.stats()
    assert out == ref[:ref.index(eos) + 1]
    assert after["rows_in_use"] == 0
    assert after["retired_total"] == before["retired_total"] + 1


# --- zero-recompile invariant ----------------------------------------------

def test_warmup_then_mixed_traffic_zero_recompiles():
    """After ``warmup()`` a mixed sweep — short and long prompts, short
    and long outputs, KV bucket growth 16→32, join groups of 1..4 —
    never misses the AOT cache."""
    with _engine() as eng:
        eng.warmup()
        miss0 = aot_cache.stats()["misses"]
        reqs = [eng.submit([1 + i % 7] * (1 + 3 * i), max_new_tokens=3 + i)
                for i in range(4)]
        for r in reqs:
            eng.result(r)
        # long prompt: prompt bucket 32 forces a KV grow hop mid-service
        eng.generate([2] * 20, max_new_tokens=8)
        assert eng.stats()["kv_bucket"] == 32
        assert aot_cache.stats()["misses"] == miss0, \
            "mixed-length traffic recompiled after warmup"


def test_warmup_is_idempotent():
    eng = _engine()
    try:
        assert eng.warmup()["compiled"] == 0  # module decoder pre-warmed
    finally:
        eng.close()


# --- admission control / resilience ----------------------------------------

def test_bad_request_rejected_at_submit():
    with _engine() as eng:
        with pytest.raises(BadRequestError):
            eng.submit([], max_new_tokens=4)
        with pytest.raises(BadRequestError):
            eng.submit([VOCAB + 1], max_new_tokens=4)
        with pytest.raises(BadRequestError):
            eng.submit([1] * 31, max_new_tokens=8)
        with pytest.raises(BadRequestError):
            eng.submit([1], max_new_tokens=4, temperature=-1.0)
        with pytest.raises(BadRequestError):
            eng.submit([1], max_new_tokens=4, eos_id=VOCAB + 5)


def test_queue_full_rejects_with_503_semantics():
    eng = _engine(max_queue=2)
    eng._ensure_thread = lambda: None  # keep requests queued
    try:
        eng.submit([1], max_new_tokens=2)
        eng.submit([2], max_new_tokens=2)
        with pytest.raises(ServerOverloadedError):
            eng.submit([3], max_new_tokens=2)
    finally:
        eng.close()


def test_expired_deadline_fails_queued_request():
    eng = _engine()
    eng._ensure_thread = lambda: None
    try:
        req = eng.submit([1, 2], max_new_tokens=4, timeout_ms=5)
        time.sleep(0.02)
        eng._expire_queued_locked(time.monotonic())
        with pytest.raises(DeadlineExpiredError):
            eng.result(req)
    finally:
        eng.close()


def test_deadline_mid_generation_frees_row():
    """A deadline that expires while the sequence is decoding fails the
    request at the next retire check and releases its cache row (the
    in-graph ``gen_release`` mask keeps the dead row a no-op)."""
    plan = FaultPlan(seed=3)
    plan.inject("decode.launch", probability=1.0, action="delay",
                delay_s=0.02)
    with _engine() as eng:
        with plan.armed():
            req = eng.submit([1, 2, 3], max_new_tokens=28, timeout_ms=60)
            with pytest.raises(DeadlineExpiredError):
                eng.result(req)
        deadline = time.monotonic() + 5
        while eng.stats()["rows_in_use"] and time.monotonic() < deadline:
            time.sleep(0.005)
        assert eng.stats()["rows_in_use"] == 0


def test_breaker_trips_open_and_sheds_then_recovers():
    """Persistent decode-path failure trips the circuit open (every
    in-flight request fails, like the batcher failing its batch), open
    sheds at submit with 503 semantics, and a half-open probe closes it
    once the fault clears."""
    breaker = CircuitBreaker(name="decode-test", failure_threshold=2,
                             recovery_timeout_s=0.15, success_threshold=1)
    eng = GenerationEngine(
        _decoder(), GenerationConfig(max_batch=MAX_BATCH, fused_steps=K,
                                     kv_bucket_min=16, prompt_bucket_min=4),
        breaker=breaker, retry=None)
    plan = FaultPlan(seed=11)
    plan.inject("decode.launch", probability=1.0, action="raise")
    try:
        with plan.armed():
            for _ in range(2):
                req = eng.submit([1, 2], max_new_tokens=4)
                with pytest.raises(Exception):
                    eng.result(req)
            deadline = time.monotonic() + 5
            while breaker.state != "open" and time.monotonic() < deadline:
                time.sleep(0.01)
            assert breaker.state == "open"
            with pytest.raises(CircuitOpenError):
                eng.submit([1, 2], max_new_tokens=4)
            rec = REGISTRY.snapshot(run_collectors=False)
        time.sleep(0.2)  # recovery window, fault now disarmed
        out = eng.generate([1, 2], max_new_tokens=4)  # half-open probe
        assert len(out) == 4
        assert breaker.state == "closed"
        assert rec.get('dl4j_decode_requests_total{status="shed"}', 0) >= 1
    finally:
        eng.close()


def test_close_fails_pending_requests():
    eng = _engine()
    eng._ensure_thread = lambda: None
    req = eng.submit([1], max_new_tokens=2)
    eng.close()
    with pytest.raises(RuntimeError, match="closed"):
        eng.result(req)
    with pytest.raises(RuntimeError, match="closed"):
        eng.submit([1], max_new_tokens=2)


# --- telemetry / stats / lint ----------------------------------------------

def test_decode_telemetry_series():
    snap0 = REGISTRY.snapshot(run_collectors=False)
    with _engine() as eng:
        eng.generate([1, 2, 3, 4], max_new_tokens=6)
        snap1 = REGISTRY.snapshot(run_collectors=True)
    d_tokens = (snap1["dl4j_decode_tokens_total"]
                - snap0.get("dl4j_decode_tokens_total", 0))
    assert d_tokens >= 6
    assert "dl4j_decode_batch_occupancy" in snap1
    assert "dl4j_decode_kv_rows_in_use" in snap1
    assert snap1["dl4j_decode_token_seconds"]["count"] > 0
    assert snap1["dl4j_decode_first_token_seconds"]["count"] > 0
    ok_key = 'dl4j_decode_requests_total{status="ok"}'
    assert snap1.get(ok_key, 0) >= snap0.get(ok_key, 0) + 1


def test_generation_panel_renders():
    from deeplearning4j_tpu.ui.server import UIServer

    with _engine() as eng:
        eng.generate([5, 5], max_new_tokens=3)
    panel = UIServer.get_instance()._generation_panel()
    assert "Generation (continuous batching)" in panel
    assert "dl4j_decode_tokens_total" in panel


def test_stats_shape():
    with _engine() as eng:
        eng.generate([1, 2], max_new_tokens=3)
        st = eng.stats()
    assert st["rows"] == MAX_BATCH
    assert st["joined_total"] >= 1 and st["retired_total"] >= 1
    assert st["tokens_total"] >= 3
    assert st["prefill_seconds"] > 0 and st["decode_seconds"] > 0
    assert st["buckets"]["kv"] == [16, 32]
    assert "misses" in st["aot_cache"]


def test_donation_audit_covers_decode_kinds():
    """PRG201: the program linter's train-kind set includes
    ``decode_step*``/``prefill*`` and every compiled decode/join
    executable aliases its state buffers (the KV cache is donated, not
    copied)."""
    from deeplearning4j_tpu.analysis import program

    assert "decode_step" in program.TRAIN_KIND_PREFIXES
    assert "prefill" in program.TRAIN_KIND_PREFIXES
    _decoder()  # ensure the executables exist in this process
    audit = program.donation_audit()
    kinds = {k: v for k, v in audit.items()
             if k[1].startswith(("decode_step", "prefill"))}
    assert kinds, "no decode executables were audited"
    for key, rep in kinds.items():
        assert rep["aliases"] > 0, f"{key[1]} does not donate its KV state"
        assert rep["findings"] == 0


# --- prefix caching + speculative decoding ---------------------------------

@functools.lru_cache(maxsize=None)
def _draft_decoder(seed=99) -> TransformerDecoder:
    """A 1-layer draft with the TARGET's bucket geometry. Seed 99 gives
    an untrained, disagreeing draft (the ~0%-acceptance leg); seed 7
    with the target's architecture gives an oracle draft."""
    m = TransformerEncoder(vocab_size=VOCAB, embed_dim=16, n_heads=2,
                           n_layers=1, max_len=MAX_LEN, causal=True,
                           lm_head=True, seed=seed)
    return m.decoder(max_batch=MAX_BATCH, kv_bucket_min=16,
                     prompt_bucket_min=4)


@functools.lru_cache(maxsize=None)
def _oracle_draft() -> TransformerDecoder:
    """Same architecture AND seed as the target: greedy-agrees at every
    position, so acceptance is 100% and windows emit K+1 tokens."""
    m = TransformerEncoder(vocab_size=VOCAB, embed_dim=16, n_heads=2,
                           n_layers=2, max_len=MAX_LEN, causal=True,
                           lm_head=True, seed=7)
    return m.decoder(max_batch=MAX_BATCH, kv_bucket_min=16,
                     prompt_bucket_min=4)


def test_prefix_cache_radix_unit():
    """Trie mechanics in isolation: page-aligned match with pins,
    limit/fits backoff, insert-once, LRU eviction of refcount-0 leaves
    only."""
    from deeplearning4j_tpu.parallel.prefix_cache import PrefixCache

    made = []

    def slicer(start, stop):
        made.append((start, stop))
        return {"l": {"k": np.full((stop - start, 2, 4), float(start)),
                      "v": np.full((stop - start, 2, 4), float(start))}}

    pc = PrefixCache(page_tokens=4, max_pages=2)
    toks = list(range(12))
    path = pc.insert(toks, 12, slicer)          # 3 pages, over budget
    assert made == [(0, 4), (4, 8), (8, 12)]
    assert pc.stats()["pages"] == 3              # all pinned: no eviction
    pc.release(path)
    m, nodes = pc.match(toks, limit=11)          # page-aligned, <= limit
    assert m == 8 and len(nodes) == 2
    assert nodes[0].kv["l"]["k"][0, 0, 0] == 0.0
    m2, nodes2 = pc.match(toks, limit=11, fits=lambda mm: mm <= 4)
    assert m2 == 4 and len(nodes2) == 1          # fits() backs off a page
    pc.release(nodes + nodes2)
    pc.insert(toks, 12, slicer)                  # re-pin forces eviction
    assert pc.stats()["pages"] <= 3
    assert made == [(0, 4), (4, 8), (8, 12)]     # nothing re-sliced


def test_prefix_hit_token_identical_to_cold_miss():
    """The tentpole determinism contract: requests sharing a cached
    prefix produce EXACTLY the tokens of a cold-cache run and of the
    sequential reference — the cached pages are bit-identical to the
    prefill they came from."""
    dec = _decoder()
    shared = [3, 1, 4, 1, 5, 9, 2, 6]
    prompts = [shared + [i + 1, i + 2] for i in range(5)]
    refs = [dec.generate(p, 6) for p in prompts]
    with _engine(prefix_cache=True, prefix_page=4) as eng:
        cold = [eng.generate(p, max_new_tokens=6) for p in prompts]
        hot = [eng.generate(p, max_new_tokens=6) for p in prompts]
        st = eng.stats()
    assert cold == refs and hot == refs
    assert st["prefix_cache"]["hits"] >= 5      # the whole second sweep
    assert st["prefix_cache"]["pages"] >= 2


def test_prefix_pages_released_on_all_edges():
    """Leak contract: after finish, queued-deadline expiry, dispatch
    failure and close, every pin is returned — the tree's pages are all
    refcount-0 (evictable) again."""
    def pinned(pc):
        with pc._lock:
            total, stack = 0, [pc._root]
            while stack:
                nd = stack.pop()
                for ch in nd.children.values():
                    stack.append(ch)
                    total += ch.refs
            return total

    prompt = [5, 4, 3, 2, 1, 6, 7, 8, 2, 2]
    eng = GenerationEngine(
        _decoder(),
        GenerationConfig(max_batch=MAX_BATCH, fused_steps=K,
                         kv_bucket_min=16, prompt_bucket_min=4,
                         prefix_cache=True, prefix_page=4),
        retry=None)
    try:
        pc = eng._prefix
        eng.generate(prompt, max_new_tokens=4)          # normal finish
        eng.generate(prompt, max_new_tokens=4)          # a hit finishes too
        assert pinned(pc) == 0
        # queued-deadline expiry: loop suppressed so the request expires
        # in the queue holding its pins
        eng._ensure_thread = lambda: None
        req = eng.submit(prompt, max_new_tokens=4, timeout_ms=1)
        assert pinned(pc) > 0
        time.sleep(0.01)
        eng._ensure_thread = type(eng)._ensure_thread.__get__(eng)
        with eng._cond:
            eng._expire_queued_locked(time.monotonic())
        with pytest.raises(DeadlineExpiredError):
            eng.result(req)
        assert pinned(pc) == 0
        # dispatch failure: breaker path fails the in-flight row
        plan = FaultPlan(seed=5)
        plan.inject("decode.launch", probability=1.0, action="raise")
        with plan.armed():
            req = eng.submit(prompt, max_new_tokens=4)
            with pytest.raises(Exception):
                eng.result(req)
        assert pinned(pc) == 0
        # close with a pinned request still queued
        eng._ensure_thread = lambda: None
        req = eng.submit(prompt, max_new_tokens=4)
        assert pinned(pc) > 0
    finally:
        eng.close()
    assert pinned(pc) == 0


def test_speculative_greedy_token_identical():
    """Speculation NEVER changes tokens: with an oracle draft (100%
    acceptance) and with a disagreeing draft (~0% acceptance — the
    degraded path emits exactly the non-speculative stream), engine
    output equals the sequential reference."""
    dec = _decoder()
    prompts = [[3, 9, 1], [5, 6, 7, 8, 2, 11], [1], [14, 13, 12, 2]]
    mns = [6, 9, 4, 12]
    refs = [dec.generate(p, mn) for p, mn in zip(prompts, mns)]
    with _engine(draft_conf=_oracle_draft()) as eng:
        outs = [eng.generate(p, max_new_tokens=mn)
                for p, mn in zip(prompts, mns)]
        st = eng.stats()
    assert outs == refs
    assert st["speculative"]["accepted"] > 0     # oracle draft agrees
    with _engine(draft_conf=_draft_decoder()) as eng:
        outs2 = [eng.generate(p, max_new_tokens=mn)
                 for p, mn in zip(prompts, mns)]
        st2 = eng.stats()
    assert outs2 == refs                         # 0%-acceptance degrades
    assert st2["speculative"]["windows"] > 0     # ...but still speculated


def test_speculative_sampled_matches_reference():
    """Seeded sampling through the verifier consumes the row's PRNG
    chain exactly as sequential decode does: same (seed, temperature)
    → same tokens, at any acceptance rate."""
    dec = _decoder()
    ref = dec.generate([2, 4, 6], max_new=7, temperature=0.8, seed=42)
    with _engine(draft_conf=_draft_decoder()) as eng:
        out = eng.generate([2, 4, 6], max_new_tokens=7, temperature=0.8,
                           seed=42)
    assert out == ref


def test_spec_prefix_compose_zero_recompiles():
    """Both features together under mixed traffic (hit + miss joins,
    accept + reject windows, bucket growth) never miss the AOT cache
    after warmup, and still match the sequential reference."""
    dec = _decoder()
    shared = [7, 3, 7, 3, 7, 3, 7, 3]
    prompts = [shared + [i + 1] for i in range(3)] + [[9, 9, 2]]
    refs = [dec.generate(p, 5) for p in prompts]
    with _engine(draft_conf=_oracle_draft(), prefix_cache=True,
                 prefix_page=4) as eng:
        eng.warmup()
        miss0 = aot_cache.stats()["misses"]
        outs = [eng.generate(p, max_new_tokens=5) for p in prompts]
        outs += [eng.generate(p, max_new_tokens=5) for p in prompts]
        eng.generate([2] * 20, max_new_tokens=8)   # KV grow hop
        st = eng.stats()
    assert outs == refs + refs
    assert st["prefix_cache"]["hits"] >= 1
    assert aot_cache.stats()["misses"] == miss0, \
        "prefix/spec traffic recompiled after warmup"


def test_spec_fallback_near_context_limit():
    """When a row is within K+1 slots of max_len the iteration falls
    back to the plain fused window — output still matches the
    sequential reference all the way to the context edge."""
    dec = _decoder()
    prompt = [1, 2, 3, 4]
    mn = MAX_LEN - len(prompt)                    # decode to the edge
    ref = dec.generate(prompt, mn)
    with _engine(draft_conf=_oracle_draft()) as eng:
        out = eng.generate(prompt, max_new_tokens=mn)
    assert out == ref


def test_draft_geometry_mismatch_rejected():
    m = TransformerEncoder(vocab_size=VOCAB, embed_dim=16, n_heads=2,
                           n_layers=1, max_len=16, causal=True,
                           lm_head=True, seed=1)
    bad = m.decoder(max_batch=MAX_BATCH, kv_bucket_min=16,
                    prompt_bucket_min=4)          # max_len 16 != 32
    with pytest.raises(ValueError, match="geometry"):
        GenerationEngine(
            _decoder(),
            GenerationConfig(max_batch=MAX_BATCH, fused_steps=K,
                             kv_bucket_min=16, prompt_bucket_min=4,
                             draft_conf=bad))


def test_prefix_and_spec_telemetry_series():
    snap0 = REGISTRY.snapshot(run_collectors=False)
    shared = [4, 4, 4, 4, 8, 8, 8, 8]
    with _engine(draft_conf=_oracle_draft(), prefix_cache=True,
                 prefix_page=4) as eng:
        eng.generate(shared + [1], max_new_tokens=5)
        eng.generate(shared + [2], max_new_tokens=5)
        snap1 = REGISTRY.snapshot(run_collectors=False)
    for name in ("dl4j_prefix_cache_hits_total",
                 "dl4j_prefix_cache_misses_total",
                 "dl4j_prefix_cache_hit_tokens_total",
                 "dl4j_spec_draft_tokens_total",
                 "dl4j_spec_accepted_tokens_total"):
        assert snap1.get(name, 0) > snap0.get(name, 0), name
    assert "dl4j_prefix_cache_pages" in snap1
    assert snap1["dl4j_spec_accepted_tokens"]["count"] > 0


def test_generation_panel_includes_prefix_and_spec():
    from deeplearning4j_tpu.ui.server import UIServer

    with _engine(draft_conf=_oracle_draft(), prefix_cache=True,
                 prefix_page=4) as eng:
        eng.generate([6, 6, 6, 6, 2], max_new_tokens=4)
    panel = UIServer.get_instance()._generation_panel()
    assert "Generation — prefix cache" in panel
    assert "Generation — speculative decode" in panel
    assert "dl4j_spec_accepted_tokens" in panel


# --- Pallas attention kernels through the decode path -----------------------

@functools.lru_cache(maxsize=None)
def _kern_decoder() -> TransformerDecoder:
    """The target model with ``use_kernels=True``: same weights (seed 7)
    as ``_decoder()``, attention envelopes tuned BEFORE warm_all so the
    warmed executables bake the winners and carry the ``kern:`` tokens."""
    from deeplearning4j_tpu import kernels

    m = TransformerEncoder(vocab_size=VOCAB, embed_dim=16, n_heads=2,
                           n_layers=2, max_len=MAX_LEN, causal=True,
                           lm_head=True, seed=7, use_kernels=True)
    dec = m.decoder(max_batch=MAX_BATCH, kv_bucket_min=16,
                    prompt_bucket_min=4)
    kernels.autotune_decoder(dec, max_candidates=1, trials=1)
    dec.warm_all(fused_steps=(1, K))
    return dec


def _kern_engine(**over):
    cfg = dict(max_batch=MAX_BATCH, fused_steps=K, kv_bucket_min=16,
               prompt_bucket_min=4)
    cfg.update(over)
    return GenerationEngine(_kern_decoder(), GenerationConfig(**cfg))


def test_kernels_decoder_token_identical_and_zero_recompile():
    """use_kernels greedy decode (flash prefill + paged decode steps)
    is token-identical to the stock decoder for every prompt, at K=1
    and fused K, with ZERO recompiles after warmup — and every step key
    carries both attention kernel tokens."""
    dec = _decoder()
    kdec = _kern_decoder()
    tag = kdec._ktag()
    assert "kern:flash_attention:" in tag
    assert "kern:paged_decode_attention:" in tag
    prompts = [[3, 9, 1], [5, 6, 7, 8, 2, 11], [1], [9] * 12]
    mns = [6, 9, 4, 8]
    m0 = aot_cache.stats()["misses"]
    for p, mn in zip(prompts, mns):
        ref = dec.generate(p, mn)
        assert kdec.generate(p, mn) == ref
        assert kdec.generate(p, mn, fused_steps=K) == ref
    assert aot_cache.stats()["misses"] == m0, \
        "kernel-routed decode recompiled after warmup"


def test_kernels_engine_continuous_matches_sequential():
    """Continuous batching over the kernel-routed decoder: mixed
    prompt/output lengths churn rows at ragged per-row cache occupancy
    (the paged gather's DMA-skip sees every row at a different page
    count) and each sequence equals the STOCK sequential reference."""
    dec = _decoder()
    prompts = [[3, 9, 1], [5, 6, 7, 8, 2, 11], [1], [14, 13, 12, 2],
               [9, 9, 2, 3, 4, 5, 6, 1]]
    mns = [6, 9, 4, 12, 5]
    refs = [dec.generate(p, mn) for p, mn in zip(prompts, mns)]
    with _kern_engine() as eng:
        warm = eng.warmup()
        assert warm["kernels"]["enabled"]
        assert "kern:flash_attention:" in warm["kernels"]["tag"]
        m0 = aot_cache.stats()["misses"]
        reqs = [eng.submit(p, max_new_tokens=mn)
                for p, mn in zip(prompts, mns)]
        outs = [eng.result(r) for r in reqs]
        st = eng.stats()
    assert outs == refs
    assert aot_cache.stats()["misses"] == m0
    assert st["kernels"]["enabled"] and "kern:" in st["kernels"]["tag"]


def test_kernels_prefix_attached_pages_token_identical():
    """Prefix-cache hits attach cached KV pages and decode continues at
    an offset position — the paged kernel's gather must read attached
    pages exactly like prefilled ones (cold run, hot run, and the stock
    sequential reference all agree)."""
    dec = _decoder()
    shared = [3, 1, 4, 1, 5, 9, 2, 6]
    prompts = [shared + [i + 1, i + 2] for i in range(4)]
    refs = [dec.generate(p, 6) for p in prompts]
    with _kern_engine(prefix_cache=True, prefix_page=4) as eng:
        cold = [eng.generate(p, max_new_tokens=6) for p in prompts]
        hot = [eng.generate(p, max_new_tokens=6) for p in prompts]
        st = eng.stats()
    assert cold == refs and hot == refs
    assert st["prefix_cache"]["hits"] >= 4


def test_kernel_bearing_decode_kinds_donate_and_audit_clean():
    """PRG201/PRG207 satellite: every kernel-bearing decode/prefill
    executable compiled this process donates its KV state and carries
    zero lint findings (PRG207 verified the tokens at compile time)."""
    from deeplearning4j_tpu.analysis import program

    _kern_decoder()  # ensure the executables exist in this process
    audit = program.donation_audit()
    kinds = {k: v for k, v in audit.items()
             if "kern:" in k[1]
             and k[1].startswith(("decode_step", "prefill"))}
    assert kinds, "no kernel-bearing decode executable was audited"
    for key, rep in kinds.items():
        assert rep["aliases"] > 0, f"{key[1]} does not donate its KV state"
        assert rep["findings"] == 0, f"{key[1]} has lint findings"


def test_kernels_retune_mints_new_decoder_executable():
    """A retune bumps the tuning digest, every ``kern:``-keyed step
    re-mints (AOT misses), and the retuned paged kernel is still
    token-identical. Runs LAST of the kernel-decode tests: it leaves
    the tuning table mutated."""
    from deeplearning4j_tpu import kernels

    dec = _decoder()
    kdec = _kern_decoder()
    prompt = [2, 4, 6]
    ref = dec.generate(prompt, 5)
    assert kdec.generate(prompt, 5) == ref
    tag0 = kdec._ktag()
    kid = "paged_decode_attention"
    env = next(e for k_, e in kernels.decoder_envelopes(kdec)
               if k_ == kid and e.tk == 16)
    cur = tuple(kernels.TUNING.winner(kid, env.key)["tiling"])
    alt = next(tuple(t) for t in
               kernels.REGISTRY.get(kid).candidates(env)
               if tuple(t) != cur)
    m0 = aot_cache.stats()["misses"]
    kernels.TUNING.record(kid, env.key, alt, 0.0)
    assert kdec._ktag() != tag0
    assert kdec.generate(prompt, 5) == ref
    assert aot_cache.stats()["misses"] > m0, \
        "a retuned kernel must be a NEW executable"


def test_donation_audit_covers_spec_and_prefix_kinds():
    """PRG201 satellite: the new decode-state consumers are in the
    audit's train-kind set, every compiled one donates, and the suffix
    prefill (shared refcounted pages) is deliberately exempt."""
    from deeplearning4j_tpu.analysis import program

    for kind in ("spec_verify", "spec_sync", "prefix_attach",
                 "prefix_join"):
        assert kind in program.TRAIN_KIND_PREFIXES
    with _engine(draft_conf=_oracle_draft(), prefix_cache=True,
                 prefix_page=4) as eng:
        eng.generate([1, 2, 3, 4, 5], max_new_tokens=4)
        eng.generate([1, 2, 3, 4, 6], max_new_tokens=4)
    audit = program.donation_audit()
    kinds = {k: v for k, v in audit.items()
             if k[1].startswith(("spec_verify", "spec_sync",
                                 "prefix_attach", "prefix_join"))}
    assert kinds, "no spec/prefix executables were audited"
    for key, rep in kinds.items():
        assert rep["aliases"] > 0, f"{key[1]} does not donate its state"
        assert rep["findings"] == 0
