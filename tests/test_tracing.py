"""End-to-end request tracing + SLO burn-rate engine (telemetry.tracing,
telemetry.slo) threaded through the serving stack.

The invariants pinned here are the observability subsystem's acceptance
criteria: W3C ``traceparent`` round-trips over live HTTP (inbound trace
ids adopted, fresh span id minted, error paths echo the caller's header
verbatim); one trace follows a generation request across prefix-attach,
join, every fused decode window, and retirement; tracing DISABLED is
inert (``start_trace`` returns ``None``, nothing is recorded, the
request path is unchanged); tail sampling and SLO alert transitions are
replay-deterministic (same seed + same traffic → same retained trace
ids, same transition indices); flight-recorder bundles carry the
retained request traces and are pruned keep-last-N on publish; and the
``/traces`` + ``/slo`` UI endpoints serve the live snapshots.
"""

import functools
import json
import os
import urllib.error
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.conf import Activation, InputType
from deeplearning4j_tpu.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.conf.losses import LossMCXENT
from deeplearning4j_tpu.conf.multilayer import NeuralNetConfiguration
from deeplearning4j_tpu.conf.updaters import Sgd
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel.batcher import (
    BatchingConfig,
    InferenceEngine,
)
from deeplearning4j_tpu.parallel.generation import (
    GenerationConfig,
    GenerationEngine,
)
from deeplearning4j_tpu.parallel.platform import (
    ModelPlatform,
    ModelRegistry,
    TenantConfig,
)
from deeplearning4j_tpu.parallel.serving import InferenceServer
from deeplearning4j_tpu import resilience
from deeplearning4j_tpu.telemetry import REGISTRY, flightrec, tracing
from deeplearning4j_tpu.telemetry.slo import SLO, SLOMonitor
from deeplearning4j_tpu.zoo.graphs import TransformerEncoder

pytestmark = pytest.mark.obs


@pytest.fixture(autouse=True)
def _clean_tracing():
    yield
    tracing.disable()
    tracing.reset()


def _mlp(seed=0):
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Sgd(0.1))
            .list()
            .layer(DenseLayer(n_out=8, activation=Activation.TANH))
            .layer(OutputLayer(n_out=3, activation=Activation.SOFTMAX,
                               loss_fn=LossMCXENT()))
            .set_input_type(InputType.feed_forward(4)).build())
    return MultiLayerNetwork(conf).init()


def _x(rows=2):
    return np.random.default_rng(0).normal(size=(rows, 4)).astype(np.float32)


# same shape as tests/test_decode.py so the AOT cache shares every
# executable across the suite (the cache is process-global)
VOCAB = 32
MAX_LEN = 32
MAX_BATCH = 4
K = 2


@functools.lru_cache(maxsize=None)
def _decoder():
    m = TransformerEncoder(vocab_size=VOCAB, embed_dim=16, n_heads=2,
                           n_layers=2, max_len=MAX_LEN, causal=True,
                           lm_head=True, seed=7)
    return m.decoder(max_batch=MAX_BATCH, kv_bucket_min=16,
                     prompt_bucket_min=4)


def _names(trace):
    return [name for name, _, _ in trace.events]


# --- traceparent ------------------------------------------------------------

def test_traceparent_parse():
    tid, sid = "ab" * 16, "cd" * 8
    assert tracing.parse_traceparent(f"00-{tid}-{sid}-01") == (tid, sid)
    assert tracing.parse_traceparent(None) is None
    assert tracing.parse_traceparent("garbage") is None
    assert tracing.parse_traceparent(f"00-{tid[:-2]}-{sid}-01") is None
    assert tracing.parse_traceparent(f"ff-{tid}-{sid}-01") is None
    assert tracing.parse_traceparent(f"00-{'0' * 32}-{sid}-01") is None


def test_disabled_tracing_is_inert():
    tracing.disable()
    tracing.reset()
    assert tracing.start_trace("predict") is None
    tracing.trace_event(None, "queued")   # all helpers no-op on None
    tracing.finish_trace(None, "ok")
    eng = InferenceEngine(_mlp(), BatchingConfig(max_batch=2))
    try:
        out, trace = eng.predict_traced(_x())
        assert trace is None
        assert np.asarray(out).shape == (2, 3)
    finally:
        eng.close()
    assert tracing.stats()["started"] == 0
    assert tracing.traces() == []


# --- batcher lifecycle ------------------------------------------------------

def test_batcher_trace_chain():
    tracing.enable(seed=1, sample_every=1)
    eng = InferenceEngine(_mlp(), BatchingConfig(max_batch=2))
    try:
        out, trace = eng.predict_traced(_x())
    finally:
        eng.close()
    assert np.asarray(out).shape == (2, 3)
    assert trace.status == "ok"
    names = _names(trace)
    assert [n for n in names if n in ("queued", "admitted", "grouped",
                                      "launched", "demuxed")] == \
        ["queued", "admitted", "grouped", "launched", "demuxed"]
    # the retained trace is the same record the caller saw
    assert trace.trace_id in [t.trace_id for t in tracing.traces()]


# --- HTTP round-trip --------------------------------------------------------

def test_http_traceparent_round_trip():
    tracing.enable(seed=2, sample_every=1)
    server = InferenceServer(_mlp()).start(port=0)
    try:
        base = f"http://127.0.0.1:{server.port}"
        hdr = f"00-{'ab' * 16}-{'cd' * 8}-01"
        req = urllib.request.Request(
            base + "/predict",
            data=json.dumps({"inputs": [_x().tolist()]}).encode(),
            headers={"Content-Type": "application/json",
                     "traceparent": hdr})
        with urllib.request.urlopen(req, timeout=30) as r:
            echoed = r.headers["traceparent"]
            json.loads(r.read())
        # inbound trace id adopted, NEW span id minted for this hop
        parsed = tracing.parse_traceparent(echoed)
        assert parsed is not None and parsed[0] == "ab" * 16
        assert parsed[1] != "cd" * 8

        # no inbound header: a fresh, well-formed root trace
        req = urllib.request.Request(
            base + "/predict",
            data=json.dumps({"inputs": [_x().tolist()]}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as r:
            fresh = tracing.parse_traceparent(r.headers["traceparent"])
            json.loads(r.read())
        assert fresh is not None and fresh[0] != "ab" * 16

        # error responses echo the CALLER's header verbatim so the
        # client can still correlate the failure
        bad = urllib.request.Request(
            base + "/predict", data=b'{"nope": 1}',
            headers={"Content-Type": "application/json",
                     "traceparent": hdr})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(bad, timeout=10)
        assert ei.value.code == 400
        assert ei.value.headers["traceparent"] == hdr
    finally:
        server.stop()


# --- generation lifecycle ---------------------------------------------------

def test_generation_trace_across_prefix_join_windows_retire():
    """One trace follows a generation request end to end: queued →
    (prefix_attach | prefill) → join → every fused decode window →
    retirement, with the inbound traceparent's trace id adopted."""
    tracing.enable(seed=3, sample_every=1)
    cfg = GenerationConfig(max_batch=MAX_BATCH, fused_steps=K,
                           kv_bucket_min=16, prompt_bucket_min=4,
                           prefix_cache=True, prefix_page=4)
    eng = GenerationEngine(_decoder(), cfg)
    try:
        shared = [5, 9, 2, 7, 1, 4, 8, 3]
        h1 = eng.submit(shared + [6], max_new_tokens=6)
        out1 = eng.result(h1)
        hdr = f"00-{'ef' * 16}-{'12' * 8}-01"
        h2 = eng.submit(shared + [11], max_new_tokens=6, traceparent=hdr)
        out2 = eng.result(h2)
    finally:
        eng.close()
    assert len(out1) == 6 and len(out2) == 6

    cold, warm = h1.trace, h2.trace
    assert cold.status == "done" and warm.status == "done"
    assert cold.attrs["tokens"] == 6

    n1 = _names(cold)
    assert n1[0] == "queued" and "join" in n1 and "prefill" in n1
    assert "first_token" in n1
    assert n1.count("decode_window") >= 2  # 6 tokens / K=2 → 3 windows

    # the second request attaches cached prefix pages instead of a
    # cold prefill, under the SAME (adopted) trace
    n2 = _names(warm)
    assert "prefix_attach" in n2 and "prefill" not in n2
    assert n2.count("decode_window") >= 2
    assert warm.trace_id == "ef" * 16
    assert warm.parent_id == "12" * 8

    # per-window attrs feed the bench's stage breakdown
    windows = [a for name, _, a in warm.events if name == "decode_window"]
    assert all(w["k"] == K and "ms" in w and "kv_bucket" in w
               for w in windows)
    bd = tracing.stage_breakdown()
    assert bd["decode_window"]["count"] >= 4
    assert bd["queue_wait"]["count"] >= 2


# --- deterministic tail sampling --------------------------------------------

def test_tail_sampling_replay_deterministic():
    """Same seed + same traffic → the SAME retained trace ids: ids are a
    pure function of (seed, submit counter) and the sampling decision a
    pure function of the id + status."""
    def replay(seed):
        tracing.enable(seed=seed, sample_every=4,
                       min_slow_samples=10_000)  # isolate the hash rule
        for i in range(40):
            t = tracing.start_trace("req")
            tracing.finish_trace(t, "error" if i % 7 == 3 else "ok")
        kept = [(t.trace_id, t.status) for t in tracing.traces()]
        st = tracing.stats()
        tracing.disable()
        return kept, st

    kept_a, stats_a = replay(5)
    kept_b, stats_b = replay(5)
    assert kept_a == kept_b
    assert stats_a["started"] == stats_b["started"] == 40
    assert stats_a["dropped"] == stats_b["dropped"] > 0
    # abnormal terminals are NEVER sampled away
    assert sum(1 for _, s in kept_a if s == "error") == 6
    # a different seed mints different ids
    kept_c, _ = replay(6)
    assert [i for i, _ in kept_c] != [i for i, _ in kept_a]


# --- SLO burn rates ---------------------------------------------------------

def test_slo_burn_rate_transitions_replay_deterministic():
    """Alert state is a pure function of the observation stream: two
    seeded replays of the same traffic fire warn → page → recovery at
    identical observation indices, and hysteresis clears the alert only
    after ``clear_after`` consecutive clean evaluations."""
    cfg = SLO(error_rate=0.1, short_window=8, long_window=16,
              min_samples=8, warn_burn=1.0, page_burn=4.0, clear_after=4)

    def drive(mon):
        states = []
        for i in range(40):
            states.append(mon.observe("t", ok=not (8 <= i < 20)))
        for _ in range(40):
            states.append(mon.observe("t", ok=True))
        return states

    m1, m2 = SLOMonitor(cfg, seed=3), SLOMonitor(cfg, seed=3)
    s1, s2 = drive(m1), drive(m2)
    assert s1 == s2
    assert "page" in s1
    t1, t2 = m1.transitions("t"), m2.transitions("t")
    assert t1 == t2  # same transition indices, same burn snapshots
    assert [t["to"] for t in t1][:2] == ["warn", "page"]
    assert all(t["index"] == u["index"] for t, u in zip(t1, t2))
    # recovered: the error burst aged out of both windows and the
    # clear_after streak elapsed
    assert m1.state("t") == "ok"
    snap = m1.snapshot()["t"]
    assert snap["observations"] == 80
    assert snap["burn_rates"]["error_rate"]["short"] == 0.0


def test_platform_slo_surface(tmp_path):
    """The serving platform observes every judged outcome into its own
    monitor and surfaces it through stats(), resilience.status(), and
    the dl4j_slo_* gauges."""
    reg = ModelRegistry(tmp_path)
    reg.publish("m", _mlp(seed=1))
    cfg = SLO(error_rate=0.5, latency_p95_ms=60_000.0,
              short_window=4, long_window=8, min_samples=4)
    with ModelPlatform(reg, slo=cfg) as plat:
        plat.deploy("m", config=TenantConfig(
            batching=BatchingConfig(max_batch=4)))
        for _ in range(6):
            plat.predict("m", _x())
        st = plat.stats()["m"]["slo"]
        assert st["state"] == "ok"
        assert "latency_p95" in st["burn_rates"]
        res = resilience.status()
        assert res["slo"]["tenants"]["m"]["state"] == "ok"
        snap = REGISTRY.snapshot()
        assert snap.get('dl4j_slo_state{tenant="m"}') == 0
        key = ('dl4j_slo_burn_rate{objective="latency_p95",'
               'tenant="m",window="short"}')
        assert key in snap or 'objective="latency_p95"' in str(snap)


# --- flight recorder --------------------------------------------------------

def test_flightrec_bundle_traces_and_keep_last_n(tmp_path, monkeypatch):
    tracing.enable(seed=2, sample_every=1)
    t = tracing.start_trace("req")
    tracing.trace_event(t, "queued")
    tracing.finish_trace(t, "error")

    rec = flightrec.FlightRecorder(capacity=4)
    out = rec.dump_bundle(str(tmp_path / "bundle_a"), reason="test")
    assert out == str(tmp_path / "bundle_a")
    traces_doc = json.loads((tmp_path / "bundle_a" / "traces.json")
                            .read_text())
    assert [tr["trace_id"] for tr in traces_doc["traces"]] == [t.trace_id]
    manifest = json.loads((tmp_path / "bundle_a" / "manifest.json")
                          .read_text())
    assert manifest["request_trace_ids"] == [t.trace_id]
    assert "traces.json" in manifest["files"]

    # keep-last-N retention on publish: a chaos soak dumping a bundle
    # per crash must not fill the disk
    monkeypatch.setenv("DL4J_FLIGHTREC_KEEP", "3")
    os.utime(tmp_path / "bundle_a", (999, 999))
    for i in range(6):
        d = str(tmp_path / f"bundle_{i:02d}")
        rec.dump_bundle(d, reason="soak")
        os.utime(d, (1000 + i, 1000 + i))
    survivors = sorted(p.name for p in tmp_path.iterdir())
    assert survivors == ["bundle_03", "bundle_04", "bundle_05"]


# --- UI endpoints -----------------------------------------------------------

def test_traces_and_slo_ui_endpoints():
    from deeplearning4j_tpu.ui.server import UIServer

    tracing.enable(seed=4, sample_every=1)
    t = tracing.start_trace("demo")
    tracing.trace_event(t, "queued")
    tracing.finish_trace(t, "error")
    mon = SLOMonitor(SLO(error_rate=0.1, short_window=4, long_window=8,
                         min_samples=2), seed=0)
    for _ in range(4):
        mon.observe("ui-tenant", ok=True)

    ui = UIServer()
    port = ui.start(port=0)
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/traces", timeout=10) as r:
            doc = json.loads(r.read())
        assert doc["stats"]["started"] >= 1
        ours = [tr for tr in doc["traces"]
                if tr["trace_id"] == t.trace_id]
        assert ours and ours[0]["status"] == "error"
        assert ours[0]["events"][0]["name"] == "queued"
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/slo", timeout=10) as r:
            doc = json.loads(r.read())
        assert doc["tenants"]["ui-tenant"]["state"] == "ok"
    finally:
        ui.stop()


def test_chrome_trace_export_shape(tmp_path):
    tracing.enable(seed=6, sample_every=1)
    t = tracing.start_trace("req")
    tracing.trace_event(t, "queued")
    tracing.finish_trace(t, "ok")
    doc = tracing.export_chrome_trace(str(tmp_path / "trace.json"))
    slices = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    instants = [e for e in doc["traceEvents"] if e["ph"] == "i"]
    assert slices and slices[0]["args"]["trace_id"] == t.trace_id
    assert instants and instants[0]["name"] == "queued"
    json.loads((tmp_path / "trace.json").read_text())
