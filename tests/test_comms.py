"""Unified collective scheduler + cross-mesh resharding (comms/).

Pins the PR-12 contracts:

- plan determinism: same tree + intent -> identical CollectivePlan
  digest, in-process (cache hit) and across processes;
- choice rules: variadic single-exchange for sub-threshold trees,
  densified accumulation for many-tiny-leaf buckets, masked-psum gather
  on this container's check_rep jax with the native-all-gather branch
  behind the probe seam;
- bit-identity: scheduler-routed exchanges == the pre-scheduler
  primitives (inline legacy copies below) on the simulated 8-device
  mesh, and every scheduler-routed ParallelWrapper mode == its legacy
  route on real training;
- plan digests key the AOT cache (changed layout -> new executable,
  identical rebuild -> zero recompiles);
- PRG205 understands plans (promised reduce-scatter compiled to
  all-reduce -> ERROR);
- cross-mesh reshard of a live training state bitwise == the host
  gather/scatter route; publish_to_engine serves the trained weights
  with zero recompiles.
"""

import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.comms import reshard as _  # noqa: F401 (package)
from deeplearning4j_tpu.comms import scheduler
from deeplearning4j_tpu.comms.reshard import (
    publish_to_engine,
    reshard,
    reshard_training_state,
)
from deeplearning4j_tpu.parallel.compression import (
    bucketed_all_gather,
    bucketed_psum,
    bucketed_psum_scatter,
)
from deeplearning4j_tpu.parallel.mesh import DATA_AXIS, shard_map

pytestmark = pytest.mark.comms


def _mesh(n=4):
    return Mesh(np.array(jax.devices()[:n]), (DATA_AXIS,))


def _tree(rng, rows=4):
    return {
        "a": jnp.asarray(rng.normal(size=(rows, 8, 3)).astype(np.float32)),
        "b": jnp.asarray(rng.normal(size=(rows, 2)).astype(np.float32)),
        "c": [jnp.asarray(rng.normal(size=(rows, 17)).astype(np.float32)),
              jnp.asarray(rng.normal(size=(rows, 1)).astype(np.float32))],
    }


def _bit_identical(a, b):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# --------------------------------------------------------------------------
# the pre-scheduler primitives, inline (the legacy route the scheduler
# must reproduce bitwise)
# --------------------------------------------------------------------------

def _legacy_psum(tree, axis_name, bucket_bytes=None):
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    if not leaves:
        return tree
    if bucket_bytes is None or len(leaves) <= 1:
        return jax.tree_util.tree_unflatten(
            treedef, list(jax.lax.psum(tuple(leaves), axis_name)))
    sizes = [l.size * l.dtype.itemsize for l in leaves]
    out = [None] * len(leaves)
    pin = None
    for bucket in scheduler.bucket_partition(sizes, int(bucket_bytes)):
        vals = tuple(leaves[i] for i in bucket)
        if pin is not None:
            pinned = jax.lax.optimization_barrier(vals + (pin,))
            vals = tuple(pinned[:-1])
        red = jax.lax.psum(vals, axis_name)
        pin = red[0]
        for i, r in zip(bucket, red):
            out[i] = r
    return jax.tree_util.tree_unflatten(treedef, out)


def _legacy_psum_scatter(tree, axis_name, bucket_bytes=None):
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    if not leaves:
        return tree

    def scatter(vals):
        return jax.lax.psum_scatter(vals, axis_name, scatter_dimension=0,
                                    tiled=True)

    if bucket_bytes is None or len(leaves) <= 1:
        return jax.tree_util.tree_unflatten(treedef,
                                            list(scatter(tuple(leaves))))
    sizes = [l.size * l.dtype.itemsize for l in leaves]
    out = [None] * len(leaves)
    pin = None
    for bucket in scheduler.bucket_partition(sizes, int(bucket_bytes)):
        vals = tuple(leaves[i] for i in bucket)
        if pin is not None:
            pinned = jax.lax.optimization_barrier(vals + (pin,))
            vals = tuple(pinned[:-1])
        red = scatter(vals)
        pin = red[0]
        for i, r in zip(bucket, red):
            out[i] = r
    return jax.tree_util.tree_unflatten(treedef, out)


def _legacy_all_gather(tree, axis_name, index, full_sizes,
                       bucket_bytes=None):
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    if not leaves:
        return tree
    contribs = []
    for sl, full in zip(leaves, full_sizes):
        m = sl.shape[0]
        contribs.append(jax.lax.dynamic_update_slice(
            jnp.zeros((int(full),), sl.dtype), sl, (index * m,)))
    return _legacy_psum(jax.tree_util.tree_unflatten(treedef, contribs),
                        axis_name, bucket_bytes)


# --------------------------------------------------------------------------
# plans
# --------------------------------------------------------------------------

def test_plan_determinism_and_cache():
    rng = np.random.default_rng(0)
    tree = _tree(rng)
    before = scheduler.stats()
    p1 = scheduler.plan_for(tree, "all_reduce", DATA_AXIS, 64)
    p2 = scheduler.plan_for(tree, "all_reduce", DATA_AXIS, 64)
    assert p1.digest == p2.digest and p1 is p2
    after = scheduler.stats()
    assert after["plan_cache_hits"] >= before["plan_cache_hits"] + 1
    # layout changes change the digest; intent changes change the digest
    # (64 packs every leaf alone; 500 packs three together)
    p3 = scheduler.plan_for(tree, "all_reduce", DATA_AXIS, 500)
    assert p3.buckets != p1.buckets
    assert p3.digest != p1.digest
    flat = [jnp.zeros((16,)), jnp.zeros((16,))]
    p4 = scheduler.plan_for(flat, "reduce_scatter", DATA_AXIS, 64)
    p5 = scheduler.plan_for(flat, "all_reduce", DATA_AXIS, 64)
    assert p4.digest != p5.digest
    # registry lookup round-trips (the PRG205 path)
    assert scheduler.lookup_plan(p1.digest) is p1


def test_plan_digest_identical_across_processes():
    code = (
        "import jax.numpy as jnp;"
        "from deeplearning4j_tpu.comms import scheduler;"
        "t={'a': jnp.zeros((4,8,3)), 'b': jnp.zeros((4,2)),"
        " 'c':[jnp.zeros((4,17)), jnp.zeros((4,1))]};"
        "print(scheduler.plan_for(t,'all_reduce','data',64).digest)"
    )
    out = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        check=True, env={"JAX_PLATFORMS": "cpu", "PATH": "/usr/bin:/bin",
                         "PYTHONPATH": "/root/repo"})
    rng = np.random.default_rng(0)
    here = scheduler.plan_for(_tree(rng), "all_reduce", DATA_AXIS, 64)
    assert out.stdout.strip() == here.digest


def test_plan_choice_rules():
    rng = np.random.default_rng(1)
    # sub-threshold tree -> ONE variadic exchange, no barrier chain
    p = scheduler.plan_for(_tree(rng), "all_reduce", DATA_AXIS, None)
    assert p.launches() == 1 and p.choices == ("variadic",)
    # many tiny same-dtype leaves in one bucket -> densify
    tiny = [jnp.zeros((4, 3), jnp.float32) for _ in range(12)]
    p = scheduler.plan_for(tiny, "all_reduce", DATA_AXIS, 10 ** 9)
    assert p.choices == ("densify",)
    # mixed dtypes never densify
    mixed = ([jnp.zeros((4, 3), jnp.float32) for _ in range(8)]
             + [jnp.zeros((4, 3), jnp.bfloat16) for _ in range(4)])
    p = scheduler.plan_for(mixed, "all_reduce", DATA_AXIS, 10 ** 9)
    assert "densify" not in p.choices
    # a big leaf in the bucket disables densify
    big = [jnp.zeros((4, 3), jnp.float32) for _ in range(8)] \
        + [jnp.zeros((64, 1024), jnp.float32)]
    p = scheduler.plan_for(big, "all_reduce", DATA_AXIS, 10 ** 9)
    assert "densify" not in p.choices
    # reduce-scatter never densifies (layout-changing)
    flat = [jnp.zeros((16,), jnp.float32) for _ in range(12)]
    p = scheduler.plan_for(flat, "reduce_scatter", DATA_AXIS, 10 ** 9)
    assert set(p.choices) == {"variadic"}
    # gather: masked psum on this check_rep jax, native behind the probe
    p = scheduler.plan_for([jnp.zeros((4,))], "all_gather", DATA_AXIS,
                           full_sizes=[16])
    assert p.choices == (
        ("all_gather",) if scheduler.NATIVE_ALL_GATHER
        else ("masked_psum",))


def test_native_probe_seam_changes_choice_and_digest(monkeypatch):
    sl = [jnp.zeros((4,), jnp.float32)]
    fallback = scheduler.plan_for(sl, "all_gather", DATA_AXIS,
                                  full_sizes=[16])
    monkeypatch.setattr(scheduler, "NATIVE_ALL_GATHER", True)
    native = scheduler.plan_for(sl, "all_gather", DATA_AXIS,
                                full_sizes=[16])
    assert native.choices == ("all_gather",)
    assert fallback.choices == ("masked_psum",)
    assert native.digest != fallback.digest  # never aliases an executable


def test_unknown_intent_raises():
    with pytest.raises(ValueError, match="intent"):
        scheduler.plan_for([jnp.zeros((4,))], "gossip", DATA_AXIS)


def test_bucket_partition_shared_implementation():
    from deeplearning4j_tpu.parallel import compression

    assert compression.bucket_partition is scheduler.bucket_partition
    assert compression.bucket_layout is scheduler.bucket_layout
    from deeplearning4j_tpu.sharding.zero import ZeroSpec

    z = ZeroSpec({"w": np.zeros((10, 3), np.float32)}, 4)
    assert z.layout_bytes(None) == [z.padded_sizes[0] * 4]


# --------------------------------------------------------------------------
# bit-identity vs the legacy primitives
# --------------------------------------------------------------------------

@pytest.mark.parametrize("bucket_bytes", [None, 64, 10 ** 9])
def test_scheduler_psum_bitwise_vs_legacy(bucket_bytes):
    mesh = _mesh()
    rng = np.random.default_rng(2)
    tree = _tree(rng)
    specs = jax.tree_util.tree_map(lambda _: P(DATA_AXIS), tree)
    got = jax.jit(shard_map(
        lambda t: bucketed_psum(t, DATA_AXIS, bucket_bytes), mesh,
        in_specs=(specs,), out_specs=specs))(tree)
    want = jax.jit(shard_map(
        lambda t: _legacy_psum(t, DATA_AXIS, bucket_bytes), mesh,
        in_specs=(specs,), out_specs=specs))(tree)
    _bit_identical(got, want)


def test_densified_bucket_bitwise_vs_legacy():
    mesh = _mesh()
    rng = np.random.default_rng(3)
    tiny = {str(i): jnp.asarray(rng.normal(size=(4, 3)).astype(np.float32))
            for i in range(12)}
    plan = scheduler.plan_for(tiny, "all_reduce", DATA_AXIS, 10 ** 9)
    assert plan.choices == ("densify",)   # the choice actually exercises
    specs = jax.tree_util.tree_map(lambda _: P(DATA_AXIS), tiny)
    got = jax.jit(shard_map(
        lambda t: bucketed_psum(t, DATA_AXIS, 10 ** 9), mesh,
        in_specs=(specs,), out_specs=specs))(tiny)
    want = jax.jit(shard_map(
        lambda t: _legacy_psum(t, DATA_AXIS, 10 ** 9), mesh,
        in_specs=(specs,), out_specs=specs))(tiny)
    _bit_identical(got, want)


@pytest.mark.parametrize("bucket_bytes", [None, 8, 10 ** 9])
def test_scheduler_zero_exchange_bitwise_vs_legacy(bucket_bytes):
    """reduce-scatter + all-gather round trip == legacy, bitwise."""
    mesh = _mesh()
    rng = np.random.default_rng(4)
    flat = tuple(jnp.asarray(rng.normal(size=(16,)).astype(np.float32))
                 for _ in range(3))
    full = [16, 16, 16]

    def routed(t):
        sl = bucketed_psum_scatter(t, DATA_AXIS, bucket_bytes)
        idx = jax.lax.axis_index(DATA_AXIS)
        return bucketed_all_gather(sl, DATA_AXIS, idx, full, bucket_bytes)

    def legacy(t):
        sl = _legacy_psum_scatter(t, DATA_AXIS, bucket_bytes)
        idx = jax.lax.axis_index(DATA_AXIS)
        return _legacy_all_gather(sl, DATA_AXIS, idx, full, bucket_bytes)

    in_specs = (tuple(P() for _ in flat),)
    out_specs = tuple(P() for _ in flat)
    got = jax.jit(shard_map(routed, mesh, in_specs=in_specs,
                            out_specs=out_specs))(flat)
    want = jax.jit(shard_map(legacy, mesh, in_specs=in_specs,
                             out_specs=out_specs))(flat)
    _bit_identical(got, want)


def test_native_all_gather_branch_executes(monkeypatch):
    """The fallback seam, exercised for real: with the probe forced on,
    the plan chooses the native lax.all_gather and its execution
    (observed per shard under varying out_specs — the pre-vma checker
    cannot see the output's replication, which is exactly why the probe
    gates the product path) gathers bitwise what the masked psum
    gathers."""
    mesh = _mesh()
    rng = np.random.default_rng(5)
    sl = jnp.asarray(rng.normal(size=(16,)).astype(np.float32))

    def masked(s):
        idx = jax.lax.axis_index(DATA_AXIS)
        (out,) = bucketed_all_gather((s,), DATA_AXIS, idx, [16])
        return out

    want = jax.jit(shard_map(masked, mesh, in_specs=(P(DATA_AXIS),),
                             out_specs=P()))(sl)
    monkeypatch.setattr(scheduler, "NATIVE_ALL_GATHER", True)

    def native(s):
        (out,) = bucketed_all_gather((s,), DATA_AXIS, None, [16])
        return out

    per_shard = jax.jit(shard_map(native, mesh, in_specs=(P(DATA_AXIS),),
                                  out_specs=P(DATA_AXIS)))(sl)
    stacked = np.asarray(per_shard).reshape(4, 16)
    for row in stacked:
        np.testing.assert_array_equal(row, np.asarray(want))


# --------------------------------------------------------------------------
# wrapper routing: every explicit-exchange mode through the scheduler
# bit-identical to the legacy route
# --------------------------------------------------------------------------

def _mlp(updater=None, seed=12345):
    from deeplearning4j_tpu.conf import Activation, InputType, WeightInit
    from deeplearning4j_tpu.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.conf.losses import LossMCXENT
    from deeplearning4j_tpu.conf.multilayer import NeuralNetConfiguration
    from deeplearning4j_tpu.conf.updaters import Adam
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    conf = (NeuralNetConfiguration.builder()
            .seed(seed)
            .updater(updater or Adam(learning_rate=0.05))
            .weight_init(WeightInit.XAVIER)
            .list()
            .layer(DenseLayer(n_out=16, activation=Activation.TANH))
            .layer(OutputLayer(n_out=3, activation=Activation.SOFTMAX,
                               loss_fn=LossMCXENT()))
            .set_input_type(InputType.feed_forward(12))
            .build())
    return MultiLayerNetwork(conf).init()


def _data(n=64, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 12)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, size=n)]
    return x, y


def _legacy_route(monkeypatch):
    """Point every explicit wrapper exchange at the inline legacy
    primitives (and neutralize plan-digest key differences by clearing
    the AOT cache around the run)."""
    from deeplearning4j_tpu.parallel import compression, wrapper

    monkeypatch.setattr(wrapper, "bucketed_psum", _legacy_psum)
    monkeypatch.setattr(wrapper, "bucketed_psum_scatter",
                        _legacy_psum_scatter)
    monkeypatch.setattr(compression, "bucketed_all_gather",
                        _legacy_all_gather)
    monkeypatch.setattr(compression, "bucketed_psum", _legacy_psum)


@pytest.mark.parametrize("mode_kw", [
    {"gradient_bucket_mb": 0.0002},                     # SHARED_GRADIENTS
    {"zero_optimizer": True, "gradient_bucket_mb": 0.0002},      # ZeRO
    {"zero_optimizer": True},                           # ZeRO fused
])
def test_wrapper_scheduler_route_bit_identical(mode_kw, monkeypatch):
    from deeplearning4j_tpu.datasets.iterators import ArrayDataSetIterator
    from deeplearning4j_tpu.optimize import aot_cache
    from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper

    x, y = _data(n=60)  # ragged tail over 8 workers

    def run(legacy):
        if legacy:
            _legacy_route(monkeypatch)
        aot_cache.clear()
        net = _mlp()
        pw = ParallelWrapper(net, workers=8, prefetch_buffer=0, **mode_kw)
        pw.fit(ArrayDataSetIterator(x, y, batch=16), epochs=2)
        monkeypatch.undo()
        return net

    a, b = run(legacy=False), run(legacy=True)
    _bit_identical(a.params, b.params)
    _bit_identical(a.opt_state, b.opt_state)
    aot_cache.clear()


def test_wrapper_threshold_and_averaging_scheduler_route(monkeypatch):
    from deeplearning4j_tpu.datasets.iterators import ArrayDataSetIterator
    from deeplearning4j_tpu.optimize import aot_cache
    from deeplearning4j_tpu.parallel.compression import ThresholdAlgorithm
    from deeplearning4j_tpu.parallel.wrapper import (
        ParallelWrapper,
        TrainingMode,
    )

    x, y = _data(n=64, seed=3)
    for kw in ({"threshold_algorithm": ThresholdAlgorithm(1e-3),
                "gradient_bucket_mb": 0.0002},
               {"training_mode": TrainingMode.AVERAGING,
                "averaging_frequency": 2,
                "gradient_bucket_mb": 0.0002}):
        def run(legacy):
            from deeplearning4j_tpu.datasets.iterators import (
                ArrayDataSetIterator as It,
            )

            if legacy:
                _legacy_route(monkeypatch)
            aot_cache.clear()
            net = _mlp(seed=7)
            pw = ParallelWrapper(net, workers=8, prefetch_buffer=0, **kw)
            pw.fit(It(x, y, batch=16), epochs=2)
            monkeypatch.undo()
            return net

        a, b = run(legacy=False), run(legacy=True)
        _bit_identical(a.params, b.params)
        _bit_identical(a.opt_state, b.opt_state)
    aot_cache.clear()


def test_plan_digest_keys_aot_cache_and_zero_recompiles():
    from deeplearning4j_tpu.datasets.iterators import ArrayDataSetIterator
    from deeplearning4j_tpu.optimize import aot_cache
    from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper

    x, y = _data(n=64, seed=9)
    net = _mlp(seed=21)
    pw = ParallelWrapper(net, workers=8, prefetch_buffer=0,
                         gradient_bucket_mb=0.0002)
    pw.fit(ArrayDataSetIterator(x, y, batch=16), epochs=1)
    key = pw._step._key[1]
    assert key.startswith("pw_bucketed:") and "plan:" in key
    digest = key.split("plan:")[1].split(":")[0]
    assert scheduler.lookup_plan(digest) is not None
    misses = aot_cache.stats()["misses"]
    # fresh wrapper, identical config -> same plan digest -> zero misses
    pw2 = ParallelWrapper(net, workers=8, prefetch_buffer=0,
                          gradient_bucket_mb=0.0002)
    pw2.fit(ArrayDataSetIterator(x, y, batch=16), epochs=1)
    assert aot_cache.stats()["misses"] == misses
    # changed bucket layout -> different plan -> different executable
    pw3 = ParallelWrapper(net, workers=8, prefetch_buffer=0,
                          gradient_bucket_mb=0.0005)
    pw3.fit(ArrayDataSetIterator(x, y, batch=16), epochs=1)
    assert aot_cache.stats()["misses"] > misses
    assert pw3._step._key[1] != key


# --------------------------------------------------------------------------
# PRG205 plan audit
# --------------------------------------------------------------------------

def test_prg205_flags_plan_promised_scatter_compiled_allreduce():
    from deeplearning4j_tpu.analysis import program

    mesh = _mesh()
    flat = [jnp.zeros((16,), jnp.float32) for _ in range(2)]
    plan = scheduler.plan_for(flat, "reduce_scatter", DATA_AXIS, None)

    def cheat(t):   # all-reduces where the plan promised reduce-scatter
        return [jax.lax.psum(x, DATA_AXIS) for x in t]

    jit_fn = jax.jit(shard_map(cheat, mesh,
                               in_specs=([P(), P()],),
                               out_specs=[P(), P()]))
    art = program.trace_artifact(
        jit_fn, (flat,), graph_key="t",
        fn_key=f"pw_zero:n4:b0:{plan.key_token()}", compile=False)
    hits = [f for f in program.lint_program(art) if f.rule == "PRG205"]
    assert hits and any("promised reduce-scatter" in f.message
                        for f in hits)
    assert any(f.severity == "ERROR" for f in hits)


def test_prg205_scheduler_routed_zero_step_passes():
    from deeplearning4j_tpu.analysis import program
    from deeplearning4j_tpu.sharding.zero import ZeroSpec

    mesh = _mesh()
    tree = {"w": jnp.zeros((40, 3), jnp.float32),
            "b": jnp.zeros((3,), jnp.float32)}
    z = ZeroSpec(tree, 4)
    rs_plan, ag_plan = z.exchange_plans(DATA_AXIS, 64)

    def step(t):
        sl = bucketed_psum_scatter(z.flat_padded(t), DATA_AXIS, 64)
        idx = jax.lax.axis_index(DATA_AXIS)
        return z.assemble(sl, idx, DATA_AXIS, 64)

    jit_fn = jax.jit(shard_map(step, mesh, in_specs=(P(),),
                               out_specs=P()))
    art = program.trace_artifact(
        jit_fn, (tree,), graph_key="t",
        fn_key=f"pw_zero:n4:b64:{rs_plan.key_token()}"
               f":{ag_plan.key_token()}", compile=False)
    assert [f for f in program.lint_program(art)
            if f.rule == "PRG205"] == []


def test_prg205_repo_zero_wrapper_compiles_clean():
    """The real scheduler-routed ZeRO step through the live AOT cache
    leaves no PRG205 findings (the extended audit resolves its plan
    digests and the compiled module matches)."""
    from deeplearning4j_tpu.analysis import findings, program
    from deeplearning4j_tpu.datasets.iterators import ArrayDataSetIterator
    from deeplearning4j_tpu.optimize import aot_cache
    from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper

    aot_cache.clear()
    program.reset()
    findings.LOG.clear()
    x, y = _data(n=32, seed=11)
    net = _mlp(seed=33)
    pw = ParallelWrapper(net, workers=8, prefetch_buffer=0,
                         zero_optimizer=True, gradient_bucket_mb=0.0002)
    pw.fit(ArrayDataSetIterator(x, y, batch=16), epochs=1)
    bad = [f for f in findings.LOG.items()
           if f.rule == "PRG205" and not f.waived]
    assert bad == []
    aot_cache.clear()


# --------------------------------------------------------------------------
# cross-mesh reshard
# --------------------------------------------------------------------------

def test_reshard_array_across_meshes_bitwise():
    src_mesh, dst_mesh = _mesh(8), _mesh(4)
    rng = np.random.default_rng(6)
    host = rng.normal(size=(16, 8)).astype(np.float32)
    x = jax.device_put(jnp.asarray(host),
                       NamedSharding(src_mesh, P(DATA_AXIS)))
    for spec in (P(DATA_AXIS), P(), P(None, DATA_AXIS)):
        tgt = NamedSharding(dst_mesh, spec)
        out = reshard(x, tgt)
        assert out.sharding == tgt
        np.testing.assert_array_equal(np.asarray(out), host)
    # replicated -> sharded, scalars, and host inputs all work
    s = jnp.float32(3.5)
    out = reshard(s, NamedSharding(dst_mesh, P()))
    assert float(out) == 3.5
    out = reshard(host, NamedSharding(dst_mesh, P(DATA_AXIS)))
    np.testing.assert_array_equal(np.asarray(out), host)


def test_zero_spec_device_scatter_matches_host_scatter():
    from deeplearning4j_tpu.sharding.zero import ZeroSpec

    mesh = _mesh(8)
    rng = np.random.default_rng(7)
    tree = {"w": rng.normal(size=(37, 3)).astype(np.float32),
            "b": rng.normal(size=(3,)).astype(np.float32)}
    dev_tree = jax.tree_util.tree_map(jnp.asarray, tree)
    z = ZeroSpec(tree, 8)
    host = z.scatter_host(tree, mesh, DATA_AXIS)
    dev = z.scatter(dev_tree, mesh, DATA_AXIS)
    _bit_identical(host, dev)
    for a, b in zip(jax.tree_util.tree_leaves(host),
                    jax.tree_util.tree_leaves(dev)):
        assert b.sharding == a.sharding
    # numpy input routes to the host path, same result
    _bit_identical(z.scatter(tree, mesh, DATA_AXIS), host)


def test_live_training_state_reshard_bitwise_vs_host_route():
    """The satellite pin: a live ZeRO training state on the 8-way mesh
    moves to a 4-way wrapper through comms.reshard bitwise-identically
    to the host gather/scatter round-trip — and training continues
    identically on both."""
    from deeplearning4j_tpu.datasets.iterators import ArrayDataSetIterator
    from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper

    from deeplearning4j_tpu.optimize import checkpoint as ckpt

    x, y = _data(n=64, seed=13)
    net = _mlp(seed=55)
    src = ParallelWrapper(net, workers=8, prefetch_buffer=0,
                          zero_optimizer=True)
    src.fit(ArrayDataSetIterator(x, y, batch=16), epochs=1)

    # host route: gather to host arrays, restore onto a fresh model,
    # restage a fresh 4-way wrapper from those host arrays
    src.sync_model()
    snap = ckpt.snapshot_training_state(net)
    host_net = _mlp(seed=55)
    ckpt.restore_training_state(host_net, snap)
    dst_host = ParallelWrapper(host_net, workers=4, prefetch_buffer=0,
                               zero_optimizer=True)
    dst_host._setup()

    # device route: slice-intersection hand-off, no host gather
    dst_dev = ParallelWrapper(_mlp(seed=55), workers=4, prefetch_buffer=0,
                              zero_optimizer=True)
    reshard_training_state(src, dst_dev)
    dst_dev._setup()

    _bit_identical(dst_host._params, dst_dev._params)
    _bit_identical(dst_host._state, dst_dev._state)
    _bit_identical(dst_host._opt, dst_dev._opt)
    # both continue training to the same place (re-prestage: the
    # explicit _setup above consumed the one-shot hand-off)
    reshard_training_state(src, dst_dev)
    x2, y2 = _data(n=32, seed=14)
    dst_host.fit(ArrayDataSetIterator(x2, y2, batch=8), epochs=1)
    dst_dev.fit(ArrayDataSetIterator(x2, y2, batch=8), epochs=1)
    _bit_identical(dst_host.model.params, dst_dev.model.params)
    _bit_identical(dst_host.model.opt_state, dst_dev.model.opt_state)


def test_reshard_training_state_refuses_non_exact_modes():
    from deeplearning4j_tpu.parallel.wrapper import (
        ParallelWrapper,
        TrainingMode,
    )

    src = ParallelWrapper(_mlp(), workers=8, prefetch_buffer=0)
    with pytest.raises(ValueError, match="no staged"):
        reshard_training_state(
            src, ParallelWrapper(_mlp(), workers=4, prefetch_buffer=0))
    src._setup()
    avg = ParallelWrapper(_mlp(), workers=4, prefetch_buffer=0,
                          training_mode=TrainingMode.AVERAGING)
    with pytest.raises(ValueError, match="SHARED_GRADIENTS"):
        reshard_training_state(src, avg)


# --------------------------------------------------------------------------
# publish_to_engine
# --------------------------------------------------------------------------

def test_publish_to_engine_serves_trained_weights_zero_recompiles():
    from deeplearning4j_tpu.datasets.iterators import ArrayDataSetIterator
    from deeplearning4j_tpu.optimize import aot_cache
    from deeplearning4j_tpu.parallel.batcher import (
        BatchingConfig,
        InferenceEngine,
    )
    from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper

    x, y = _data(n=64, seed=15)
    net = _mlp(seed=77)
    engine = InferenceEngine(net, BatchingConfig(max_batch=8,
                                                 max_delay_ms=5))
    try:
        engine.warmup()
        stale = np.asarray(engine.predict(x[:4]))
        pw = ParallelWrapper(net, workers=8, prefetch_buffer=0,
                             zero_optimizer=True)
        pw.fit(ArrayDataSetIterator(x, y, batch=16), epochs=1)
        misses = aot_cache.stats()["misses"]
        published = publish_to_engine(pw, engine)
        assert published is engine.model
        fresh = np.asarray(engine.predict(x[:4]))
        assert not np.array_equal(stale, fresh)  # weights actually moved
        # ground truth: the host-route output of the trained model
        pw.sync_model()
        want = np.asarray(net.output(x[:4]))
        np.testing.assert_allclose(fresh, want, rtol=1e-6, atol=1e-7)
        # the published model reuses every warmed executable
        assert aot_cache.stats()["misses"] == misses
    finally:
        engine.close()


def test_publish_to_engine_graph_opt_false_is_donation_safe():
    """A graph_opt=False engine publishes WITHOUT the inference pass's
    param copy, and an already-replicated wrapper tree reshards through
    the identity fast-path — the hand-off must still copy those leaves,
    or the wrapper's next donated train dispatch deletes the buffers
    the engine is serving from (review-round regression)."""
    from deeplearning4j_tpu.datasets.iterators import ArrayDataSetIterator
    from deeplearning4j_tpu.parallel.batcher import (
        BatchingConfig,
        InferenceEngine,
    )
    from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper

    x, y = _data(n=32, seed=17)
    net = _mlp(seed=88)
    pw = ParallelWrapper(net, workers=8, prefetch_buffer=0)
    pw.fit(ArrayDataSetIterator(x, y, batch=16), epochs=1)
    engine = InferenceEngine(net, BatchingConfig(max_batch=8,
                                                 max_delay_ms=5),
                             graph_opt=False)
    try:
        publish_to_engine(pw, engine)
        live = {id(l) for l in jax.tree_util.tree_leaves(
            (pw._params, pw._state))}
        pub = {id(l) for l in jax.tree_util.tree_leaves(
            (engine.model.params, engine.model.state))}
        assert not (live & pub), "engine serves the wrapper's live buffers"
        want = np.asarray(engine.predict(x[:4]))
        # the wrapper trains on (donating its staged trees); the engine
        # must keep serving the published snapshot
        pw.fit(ArrayDataSetIterator(x, y, batch=16), epochs=1)
        np.testing.assert_array_equal(np.asarray(engine.predict(x[:4])),
                                      want)
    finally:
        engine.close()


# --------------------------------------------------------------------------
# telemetry + UI
# --------------------------------------------------------------------------

def test_plan_counter_and_gauges_recorded():
    from deeplearning4j_tpu import telemetry

    telemetry.reset()
    scheduler.reset()
    tree = [jnp.zeros((4, 5), jnp.float32), jnp.zeros((4,), jnp.float32)]
    plan = scheduler.plan_for(tree, "all_reduce", DATA_AXIS, 32)
    snap = telemetry.REGISTRY.snapshot(run_collectors=False)
    key = ('dl4j_collective_plan_total'
           f'{{choice="{plan.choice_summary()}",intent="all_reduce"}}')
    assert snap.get(key) == 1
    assert snap.get('dl4j_collective_plan_bytes{intent="all_reduce"}') \
        == plan.bytes_moved()
    assert snap.get(
        'dl4j_collective_plan_launches{intent="all_reduce"}') \
        == plan.launches()


def test_collectives_panel_and_system_metrics():
    from deeplearning4j_tpu import telemetry
    from deeplearning4j_tpu.ui.server import UIServer
    from deeplearning4j_tpu.ui.stats import collect_system_metrics

    telemetry.reset()
    scheduler.plan_for([jnp.zeros((8,), jnp.float32)], "all_reduce",
                       DATA_AXIS)
    ui = UIServer()
    html = ui.render_html()
    assert "Collectives (scheduler)" in html
    assert "dl4j_collective_plan_total" in html
    sysm = collect_system_metrics()
    assert sysm["collective_plans"]["plans_built"] >= 1
