"""Image reader, augmentation and async prefetch tests (reference model:
datavec-data-image tests + AsyncDataSetIterator tests)."""

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import ArrayDataSetIterator
from deeplearning4j_tpu.datasets.normalizers import (
    ImagePreProcessingScaler, NormalizerMinMaxScaler, NormalizerStandardize,
    normalizer_from_state,
)
from deeplearning4j_tpu.datasets.prefetch import AsyncDataSetIterator
from deeplearning4j_tpu.datavec import (
    FileSplit, RecordReaderDataSetIterator,
)
from deeplearning4j_tpu.datavec.image import (
    CropImageTransform, FlipImageTransform, ImageLoader, ImageRecordReader,
    ParentPathLabelGenerator, PipelineImageTransform, RandomCropTransform,
    ResizeImageTransform,
)


def _write_png(path, color, size=(8, 8)):
    from PIL import Image

    arr = np.zeros((size[0], size[1], 3), np.uint8)
    arr[..., :] = color
    Image.fromarray(arr).save(path)


@pytest.fixture
def image_dir(tmp_path):
    for label, color in [("cats", (255, 0, 0)), ("dogs", (0, 0, 255))]:
        d = tmp_path / label
        d.mkdir()
        for i in range(3):
            _write_png(d / f"{i}.png", color)
    return tmp_path


def test_image_loader_hwc_and_chw(image_dir):
    p = next((image_dir / "cats").glob("*.png"))
    img = ImageLoader(4, 6, 3).as_matrix(p)
    assert img.shape == (4, 6, 3)
    assert img[0, 0, 0] == 255.0
    chw = ImageLoader(4, 6, 3, channels_first=True).as_matrix(p)
    assert chw.shape == (3, 4, 6)
    gray = ImageLoader(4, 4, 1).as_matrix(p)
    assert gray.shape == (4, 4, 1)


def test_image_record_reader_labels_sorted(image_dir):
    rr = ImageRecordReader(8, 8, 3,
                           label_generator=ParentPathLabelGenerator())
    rr.initialize(FileSplit(image_dir, allowed_extensions=["png"]))
    assert rr.labels() == ["cats", "dogs"]
    recs = list(rr)
    assert len(recs) == 6
    labels = sorted(r[1] for r in recs)
    assert labels == [0, 0, 0, 1, 1, 1]
    assert recs[0][0].shape == (8, 8, 3)


def test_image_pipeline_to_dataset(image_dir):
    rr = ImageRecordReader(8, 8, 3,
                           label_generator=ParentPathLabelGenerator())
    rr.initialize(FileSplit(image_dir, allowed_extensions=["png"]))
    it = RecordReaderDataSetIterator(rr, batch_size=4, label_index=1,
                                     num_possible_labels=2)
    it.set_preprocessor(ImagePreProcessingScaler())
    batches = list(it)
    assert batches[0].features.shape == (4, 8, 8, 3)
    assert batches[0].features.max() <= 1.0
    assert batches[0].labels.shape == (4, 2)


def test_transforms():
    import random

    rng = random.Random(0)
    img = np.arange(4 * 4 * 1, dtype=np.float32).reshape(4, 4, 1)
    flipped = FlipImageTransform(mode=1).apply(img, rng)
    np.testing.assert_allclose(flipped[0, :, 0], img[0, ::-1, 0])
    cropped = CropImageTransform(1, 1, 1, 1).apply(img, rng)
    assert cropped.shape == (2, 2, 1)
    rcrop = RandomCropTransform(2, 2).apply(img, rng)
    assert rcrop.shape == (2, 2, 1)
    resized = ResizeImageTransform(8, 8).apply(img, rng)
    assert resized.shape == (8, 8, 1)
    pipe = PipelineImageTransform([(FlipImageTransform(mode=1), 1.0),
                                   ResizeImageTransform(2, 2)])
    assert pipe.apply(img, rng).shape == (2, 2, 1)


def test_normalizer_standardize_roundtrip():
    feats = np.random.default_rng(0).normal(5.0, 3.0, (100, 4)).astype(np.float32)
    it = ArrayDataSetIterator(feats, np.zeros((100, 1)), batch=25)
    norm = NormalizerStandardize().fit(it)
    ds = DataSet(feats.copy(), np.zeros((100, 1)))
    norm.transform(ds)
    assert abs(ds.features.mean()) < 1e-4
    assert abs(ds.features.std() - 1.0) < 1e-2
    norm.revert(ds)
    np.testing.assert_allclose(ds.features, feats, atol=1e-3)
    # state round-trip (serializer hook)
    norm2 = normalizer_from_state(norm.state_dict())
    ds2 = norm2.transform(DataSet(feats.copy(), np.zeros((100, 1))))
    assert abs(ds2.features.mean()) < 1e-4


def test_normalizer_minmax():
    feats = np.array([[0.0, 10.0], [5.0, 20.0], [10.0, 30.0]], np.float32)
    it = ArrayDataSetIterator(feats, np.zeros((3, 1)), batch=3,
                              drop_last=False)
    norm = NormalizerMinMaxScaler().fit(it)
    ds = norm.transform(DataSet(feats.copy(), np.zeros((3, 1))))
    np.testing.assert_allclose(ds.features.min(0), [0, 0])
    np.testing.assert_allclose(ds.features.max(0), [1, 1])


def test_async_iterator_matches_sync_and_resets():
    feats = np.arange(40, dtype=np.float32).reshape(20, 2)
    labels = np.zeros((20, 1), np.float32)
    base = ArrayDataSetIterator(feats, labels, batch=4)
    sync = [ds.features.copy() for ds in base]
    base.reset()
    async_it = AsyncDataSetIterator(ArrayDataSetIterator(feats, labels, batch=4),
                                    queue_size=2)
    got = [np.asarray(ds.features) for ds in async_it]
    assert len(got) == len(sync)
    for a, b in zip(got, sync):
        np.testing.assert_allclose(a, b)
    # second epoch works after implicit re-iteration
    got2 = [np.asarray(ds.features) for ds in async_it]
    assert len(got2) == len(sync)


def test_async_iterator_propagates_errors():
    class Boom(ArrayDataSetIterator):
        def __iter__(self):
            yield DataSet(np.zeros((2, 2)), np.zeros((2, 1)))
            raise RuntimeError("ETL failure")

    it = AsyncDataSetIterator(Boom(np.zeros((4, 2)), np.zeros((4, 1)), batch=2))
    with pytest.raises(RuntimeError, match="ETL failure"):
        list(it)


def test_async_iterator_early_break_stops_producer():
    import threading

    feats = np.arange(200, dtype=np.float32).reshape(100, 2)
    it = AsyncDataSetIterator(
        ArrayDataSetIterator(feats, np.zeros((100, 1)), batch=2),
        queue_size=2)
    for i, ds in enumerate(it):
        if i == 1:
            break
    # generator close must have stopped the producer thread
    alive = [t for t in threading.enumerate()
             if t.name == "AsyncDataSetIterator" and t.is_alive()]
    assert not alive
