"""ComputationGraph: vertices, topo sort, shape inference, training,
serialization, gradient checks (reference oracle: ComputationGraph tests +
GradientCheckTestsComputationGraph, SURVEY.md §4)."""

import numpy as np
import pytest

from deeplearning4j_tpu.conf import Activation, InputType, WeightInit
from deeplearning4j_tpu.conf.graph import (
    ComputationGraphConfiguration,
    ElementWiseOp,
    ElementWiseVertex,
    L2NormalizeVertex,
    LayerVertex,
    MergeVertex,
    PreprocessorVertex,
    ReshapeVertex,
    ScaleVertex,
    ShiftVertex,
    StackVertex,
    SubsetVertex,
    UnstackVertex,
    VertexSpec,
)
from deeplearning4j_tpu.conf.layers import ActivationLayer, DenseLayer, OutputLayer
from deeplearning4j_tpu.conf.layers_cnn import (
    BatchNormalization,
    ConvolutionLayer,
    ConvolutionMode,
    PoolingType,
    SubsamplingLayer,
)
from deeplearning4j_tpu.conf.losses import LossMCXENT, LossMSE
from deeplearning4j_tpu.conf.multilayer import NeuralNetConfiguration
from deeplearning4j_tpu.conf.updaters import Adam, Sgd
from deeplearning4j_tpu.datasets.dataset import DataSet, MultiDataSet
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.util import serializer
from deeplearning4j_tpu.util.gradcheck import gradient_check_graph


def simple_graph_conf(seed=12345, updater=None):
    """input -> dense -> (dense_a, dense_b) -> add -> output (residual-ish)."""
    return (NeuralNetConfiguration.builder()
            .seed(seed)
            .updater(updater or Adam(learning_rate=0.02))
            .weight_init(WeightInit.XAVIER)
            .graph_builder()
            .add_inputs("in")
            .set_input_types(InputType.feed_forward(4))
            .add_layer("h", DenseLayer(n_out=8, activation=Activation.TANH),
                       "in")
            .add_layer("a", DenseLayer(n_out=8, activation=Activation.RELU),
                       "h")
            .add_vertex("res", ElementWiseVertex(op=ElementWiseOp.ADD),
                        "a", "h")
            .add_layer("out", OutputLayer(n_out=3,
                                          activation=Activation.SOFTMAX,
                                          loss_fn=LossMCXENT()), "res")
            .set_outputs("out")
            .build())


def iris_like(n=60, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 4)).astype(np.float32)
    y = np.zeros((n, 3), np.float32)
    cls = (x[:, 0] + 2 * x[:, 1] > 0).astype(int) + (x[:, 2] > 0.5)
    y[np.arange(n), cls] = 1.0
    return DataSet(x, y)


# --- vertex semantics vs numpy ---------------------------------------------

class TestVertexOps:
    def _run(self, vertex, *inputs):
        import jax.numpy as jnp

        y, _ = vertex.forward({}, {}, [jnp.asarray(x) for x in inputs],
                              train=False, rng=None)
        return np.asarray(y)

    def test_merge_concat_last_axis(self, rng):
        a, b = rng.normal(size=(2, 3)), rng.normal(size=(2, 5))
        np.testing.assert_allclose(self._run(MergeVertex(), a, b),
                                   np.concatenate([a, b], axis=-1))

    def test_elementwise_ops(self, rng):
        a, b = rng.normal(size=(4, 3)), rng.normal(size=(4, 3))
        np.testing.assert_allclose(
            self._run(ElementWiseVertex(op=ElementWiseOp.ADD), a, b), a + b,
            rtol=1e-6)
        np.testing.assert_allclose(
            self._run(ElementWiseVertex(op=ElementWiseOp.SUBTRACT), a, b),
            a - b, rtol=1e-6)
        np.testing.assert_allclose(
            self._run(ElementWiseVertex(op=ElementWiseOp.PRODUCT), a, b),
            a * b, rtol=1e-6)
        np.testing.assert_allclose(
            self._run(ElementWiseVertex(op=ElementWiseOp.AVERAGE), a, b),
            (a + b) / 2, rtol=1e-6)
        np.testing.assert_allclose(
            self._run(ElementWiseVertex(op=ElementWiseOp.MAX), a, b),
            np.maximum(a, b), rtol=1e-6)

    def test_subset_inclusive(self, rng):
        a = rng.normal(size=(2, 10))
        np.testing.assert_allclose(
            self._run(SubsetVertex(from_idx=2, to_idx=5), a), a[:, 2:6])

    def test_scale_shift(self, rng):
        a = rng.normal(size=(2, 3))
        np.testing.assert_allclose(self._run(ScaleVertex(scale_factor=2.5), a),
                                   2.5 * a, rtol=1e-6)
        np.testing.assert_allclose(self._run(ShiftVertex(shift_factor=1.5), a),
                                   a + 1.5, rtol=1e-6)

    def test_l2_normalize(self, rng):
        a = rng.normal(size=(3, 5)).astype(np.float32)
        got = self._run(L2NormalizeVertex(), a)
        want = a / np.linalg.norm(a, axis=1, keepdims=True)
        np.testing.assert_allclose(got, want, rtol=1e-5)

    def test_stack_unstack_roundtrip(self, rng):
        a, b = rng.normal(size=(2, 3)), rng.normal(size=(2, 3))
        stacked = self._run(StackVertex(), a, b)
        assert stacked.shape == (4, 3)
        np.testing.assert_allclose(
            self._run(UnstackVertex(from_idx=1, stack_size=2), stacked), b)

    def test_reshape(self, rng):
        a = rng.normal(size=(2, 6))
        got = self._run(ReshapeVertex(new_shape=(-1, 2, 3)), a)
        np.testing.assert_allclose(got, a.reshape(-1, 2, 3))


# --- config structure -------------------------------------------------------

class TestGraphConfig:
    def test_topo_order_out_of_declaration_order(self):
        # declare downstream vertex before its input
        conf = ComputationGraphConfiguration(
            network_inputs=("in",),
            network_outputs=("out",),
            vertices=(
                VertexSpec("out", LayerVertex(layer=OutputLayer(
                    n_out=2, loss_fn=LossMSE(),
                    activation=Activation.IDENTITY)), ("b",)),
                VertexSpec("b", LayerVertex(layer=DenseLayer(n_out=3)), ("a",)),
                VertexSpec("a", LayerVertex(layer=DenseLayer(n_out=3)), ("in",)),
            ),
            input_types=(InputType.feed_forward(4),),
        )
        assert conf.topo_order() == ["a", "b", "out"]

    def test_cycle_detection(self):
        conf = ComputationGraphConfiguration(
            network_inputs=("in",),
            network_outputs=("a",),
            vertices=(
                VertexSpec("a", ElementWiseVertex(), ("in", "b")),
                VertexSpec("b", LayerVertex(layer=DenseLayer(n_out=3)), ("a",)),
            ),
            input_types=(InputType.feed_forward(3),),
        )
        with pytest.raises(ValueError, match="cycle"):
            conf.topo_order()

    def test_unknown_input_raises(self):
        conf = ComputationGraphConfiguration(
            network_inputs=("in",),
            network_outputs=("a",),
            vertices=(VertexSpec("a", LayerVertex(layer=DenseLayer(n_out=3)),
                                 ("nope",)),),
            input_types=(InputType.feed_forward(3),),
        )
        with pytest.raises(ValueError, match="unknown input"):
            conf.topo_order()

    def test_json_roundtrip(self):
        conf = simple_graph_conf()
        s = conf.to_json()
        back = ComputationGraphConfiguration.from_json(s)
        assert back == conf

    def test_shape_inference_through_merge(self):
        g = (NeuralNetConfiguration.builder()
             .graph_builder()
             .add_inputs("in1", "in2")
             .set_input_types(InputType.feed_forward(3),
                              InputType.feed_forward(5))
             .add_layer("d1", DenseLayer(n_out=4), "in1")
             .add_layer("d2", DenseLayer(n_out=6), "in2")
             .add_vertex("m", MergeVertex(), "d1", "d2")
             .add_layer("out", OutputLayer(n_out=2, loss_fn=LossMSE(),
                                           activation=Activation.IDENTITY),
                        "m")
             .set_outputs("out")
             .build())
        types = g.vertex_output_types()
        assert types["m"].size == 10

    def test_cnn_to_dense_preprocessor_auto_inserted(self):
        g = (NeuralNetConfiguration.builder()
             .graph_builder()
             .add_inputs("in")
             .set_input_types(InputType.convolutional(8, 8, 3))
             .add_layer("conv", ConvolutionLayer(
                 n_out=4, kernel_size=(3, 3),
                 convolution_mode=ConvolutionMode.SAME), "in")
             .add_layer("dense", DenseLayer(n_out=10), "conv")
             .add_layer("out", OutputLayer(n_out=2, loss_fn=LossMSE(),
                                           activation=Activation.IDENTITY),
                        "dense")
             .set_outputs("out")
             .build())
        lv = g.vertex_map()["dense"].vertex
        assert lv.preprocessor is not None
        net = ComputationGraph(g).init()
        out = net.output(np.random.default_rng(0).normal(size=(2, 8, 8, 3)))
        assert np.asarray(out).shape == (2, 2)


# --- runtime ----------------------------------------------------------------

class TestGraphTraining:
    def test_fit_reduces_loss(self):
        net = ComputationGraph(simple_graph_conf()).init()
        ds = iris_like()
        first = net.fit_batch(ds)
        for _ in range(60):
            last = net.fit_batch(ds)
        assert last < first * 0.5

    def test_output_shape_and_softmax(self):
        net = ComputationGraph(simple_graph_conf()).init()
        out = np.asarray(net.output(iris_like(8).features))
        assert out.shape == (8, 3)
        np.testing.assert_allclose(out.sum(axis=1), 1.0, rtol=1e-5)

    def test_multi_input_multi_output(self):
        g = (NeuralNetConfiguration.builder()
             .seed(7).updater(Sgd(learning_rate=0.1))
             .graph_builder()
             .add_inputs("in1", "in2")
             .set_input_types(InputType.feed_forward(3),
                              InputType.feed_forward(2))
             .add_layer("d1", DenseLayer(n_out=8, activation=Activation.TANH),
                        "in1")
             .add_layer("d2", DenseLayer(n_out=8, activation=Activation.TANH),
                        "in2")
             .add_vertex("m", MergeVertex(), "d1", "d2")
             .add_layer("out1", OutputLayer(n_out=2,
                                            activation=Activation.SOFTMAX,
                                            loss_fn=LossMCXENT()), "m")
             .add_layer("out2", OutputLayer(n_out=1,
                                            activation=Activation.IDENTITY,
                                            loss_fn=LossMSE()), "m")
             .set_outputs("out1", "out2")
             .build())
        net = ComputationGraph(g).init()
        rng = np.random.default_rng(1)
        n = 32
        mds = MultiDataSet(
            features=[rng.normal(size=(n, 3)).astype(np.float32),
                      rng.normal(size=(n, 2)).astype(np.float32)],
            labels=[np.eye(2, dtype=np.float32)[rng.integers(0, 2, n)],
                    rng.normal(size=(n, 1)).astype(np.float32)])
        first = net.fit_batch(mds)
        for _ in range(40):
            last = net.fit_batch(mds)
        assert last < first
        outs = net.output(*mds.features)
        assert isinstance(outs, list) and len(outs) == 2
        assert np.asarray(outs[0]).shape == (n, 2)
        assert np.asarray(outs[1]).shape == (n, 1)

    def test_fit_dataset_iterator_and_evaluate(self):
        from deeplearning4j_tpu.datasets.iterators import ArrayDataSetIterator

        ds = iris_like(n=90)
        it = ArrayDataSetIterator(ds.features, ds.labels, 30)
        net = ComputationGraph(simple_graph_conf()).init()
        net.fit(it, epochs=30)
        ev = net.evaluate(it)
        assert ev.accuracy() > 0.8

    def test_clone_independent(self):
        net = ComputationGraph(simple_graph_conf()).init()
        other = net.clone()
        net.fit_batch(iris_like())
        assert not np.allclose(net.params_flat(), other.params_flat())

    def test_summary_smoke(self):
        net = ComputationGraph(simple_graph_conf()).init()
        s = net.summary()
        assert "Total params" in s and "res" in s

    def test_non_output_vertex_as_output_raises(self):
        g = (NeuralNetConfiguration.builder()
             .graph_builder()
             .add_inputs("in")
             .set_input_types(InputType.feed_forward(3))
             .add_layer("d", DenseLayer(n_out=4), "in")
             .set_outputs("d")
             .build())
        net = ComputationGraph(g).init()
        with pytest.raises(TypeError, match="not an output layer"):
            net.fit_batch(iris_like())


# --- serialization ----------------------------------------------------------

class TestGraphSerializer:
    def test_roundtrip_exact_resume(self, tmp_path):
        net = ComputationGraph(simple_graph_conf()).init()
        ds = iris_like()
        for _ in range(5):
            net.fit_batch(ds)
        p = tmp_path / "graph.zip"
        serializer.write_model(net, p)
        back = serializer.restore_computation_graph(p)
        np.testing.assert_allclose(back.params_flat(), net.params_flat(),
                                   rtol=1e-6)
        assert back.iteration == net.iteration
        # continued training must match exactly (same updater state)
        a = net.fit_batch(ds)
        b = back.fit_batch(ds)
        assert a == pytest.approx(b, rel=1e-5)


# --- gradient checks --------------------------------------------------------

class TestGraphGradients:
    def test_residual_graph_gradients(self):
        conf = simple_graph_conf(updater=Sgd(learning_rate=0.1))
        res = gradient_check_graph(conf, iris_like(n=8), n_samples=60)
        assert res.passed, res.failures

    def test_merge_subset_graph_gradients(self):
        g = (NeuralNetConfiguration.builder()
             .seed(3).updater(Sgd(learning_rate=0.1))
             .graph_builder()
             .add_inputs("in")
             .set_input_types(InputType.feed_forward(6))
             .add_vertex("s1", SubsetVertex(from_idx=0, to_idx=2), "in")
             .add_vertex("s2", SubsetVertex(from_idx=3, to_idx=5), "in")
             .add_layer("d1", DenseLayer(n_out=5, activation=Activation.TANH),
                        "s1")
             .add_layer("d2", DenseLayer(n_out=5,
                                         activation=Activation.SIGMOID), "s2")
             .add_vertex("m", MergeVertex(), "d1", "d2")
             .add_vertex("n", L2NormalizeVertex(), "m")
             .add_layer("out", OutputLayer(n_out=2, loss_fn=LossMSE(),
                                           activation=Activation.IDENTITY),
                        "n")
             .set_outputs("out")
             .build())
        rng = np.random.default_rng(5)
        ds = DataSet(rng.normal(size=(6, 6)).astype(np.float32),
                     rng.normal(size=(6, 2)).astype(np.float32))
        res = gradient_check_graph(g, ds, n_samples=60)
        assert res.passed, res.failures

    def test_cnn_graph_gradients(self):
        g = (NeuralNetConfiguration.builder()
             .seed(4).updater(Sgd(learning_rate=0.1))
             .graph_builder()
             .add_inputs("in")
             .set_input_types(InputType.convolutional(6, 6, 2))
             .add_layer("c1", ConvolutionLayer(
                 n_out=3, kernel_size=(3, 3),
                 convolution_mode=ConvolutionMode.SAME,
                 activation=Activation.TANH), "in")
             .add_layer("bn", BatchNormalization(), "c1")
             .add_layer("p", SubsamplingLayer(pooling_type=PoolingType.AVG,
                                              kernel_size=(2, 2),
                                              stride=(2, 2)), "bn")
             .add_layer("out", OutputLayer(n_out=2, loss_fn=LossMSE(),
                                           activation=Activation.IDENTITY),
                        "p")
             .set_outputs("out")
             .build())
        rng = np.random.default_rng(6)
        ds = DataSet(rng.normal(size=(4, 6, 6, 2)).astype(np.float32),
                     rng.normal(size=(4, 2)).astype(np.float32))
        res = gradient_check_graph(g, ds, n_samples=60)
        assert res.passed, res.failures


def test_graph_tbptt_conf_loads_and_nonseq_falls_back_to_standard():
    """A TRUNCATED_BPTT graph config loads and infers (serde must not
    break on saved models). Round 3: graph tBPTT training is implemented
    (tests/test_graph_tbptt.py); a NON-sequence batch under a tBPTT conf
    trains via the standard step, as MultiLayerNetwork does."""
    from deeplearning4j_tpu.conf.multilayer import BackpropType

    conf = (NeuralNetConfiguration.builder()
            .seed(1).updater(Adam(learning_rate=0.01))
            .graph_builder()
            .add_inputs("in")
            .add_layer("d", DenseLayer(n_out=4, activation=Activation.TANH),
                       "in")
            .add_layer("out", OutputLayer(n_out=2,
                                          activation=Activation.SOFTMAX,
                                          loss_fn=LossMCXENT()), "d")
            .set_outputs("out")
            .set_input_types(InputType.feed_forward(3))
            .backprop_type(BackpropType.TRUNCATED_BPTT, fwd=4, back=4)
            .build())
    net = ComputationGraph(conf).init()  # constructing/inferring is fine
    x = np.zeros((2, 3), np.float32)
    assert np.asarray(net.output(x)).shape == (2, 2)
    loss = net.fit_batch(DataSet(x, np.eye(2, dtype=np.float32)[[0, 1]]))
    assert np.isfinite(loss) and net.iteration == 1


def test_graph_feature_mask_propagation():
    """Feature masks reach mask-consuming layer vertices (reference
    ComputationGraph feedForwardMaskArrays): a masked tail must not
    change earlier outputs, and masked steps emit zeros."""
    from deeplearning4j_tpu.conf.layers_rnn import LSTM, RnnOutputLayer
    from deeplearning4j_tpu.datasets.dataset import MultiDataSet

    conf = (NeuralNetConfiguration.builder()
            .seed(3).updater(Adam(learning_rate=0.01))
            .graph_builder()
            .add_inputs("in")
            .add_layer("lstm", LSTM(n_out=6), "in")
            .add_layer("out", RnnOutputLayer(n_out=2,
                                             activation=Activation.SOFTMAX,
                                             loss_fn=LossMCXENT()), "lstm")
            .set_outputs("out")
            .set_input_types(InputType.recurrent(3, 8))
            .build())
    net = ComputationGraph(conf).init()
    rng = np.random.default_rng(0)
    x = rng.normal(size=(2, 8, 3)).astype(np.float32)
    fmask = np.ones((2, 8), np.float32)
    fmask[:, 5:] = 0.0  # valid prefix of 5 steps

    full = np.asarray(net.output(x, fmasks=[fmask]))
    trunc = np.asarray(net.output(x[:, :5]))
    unmasked = np.asarray(net.output(x))
    # valid prefix matches the truncated-sequence run exactly
    np.testing.assert_allclose(full[:, :5], trunc, rtol=1e-5, atol=1e-6)
    # and the mask actually reached the LSTM: masked-tail outputs differ
    # from the unmasked run (LSTM zeroes masked hidden states; a causal
    # prefix check alone would pass even if the mask were dropped)
    assert not np.allclose(full[:, 5:], unmasked[:, 5:])
    # masked-mask path actually trains too (loss finite, fit runs)
    y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, (2, 8))]
    mds = MultiDataSet(features=[x], labels=[y], features_masks=[fmask],
                       labels_masks=[fmask])
    l0 = net.fit_batch(mds)
    assert np.isfinite(l0)
