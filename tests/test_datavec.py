"""DataVec-equivalent pipeline tests (reference test model: datavec-api
reader/transform tests + dl4j-core RecordReaderDataSetIterator tests)."""

import numpy as np
import pytest

from deeplearning4j_tpu.datavec import (
    CSVRecordReader, CSVSequenceRecordReader, CollectionRecordReader,
    CollectionSequenceRecordReader, FileSplit, CollectionInputSplit,
    JsonRecordReader, LineRecordReader, NumberedFileInputSplit,
    RecordReaderDataSetIterator, RegexLineRecordReader, Schema,
    SequenceRecordReaderDataSetIterator, StringSplit, TransformProcess,
    TransformProcessRecordReader,
)
from deeplearning4j_tpu.datavec.bridge import AlignmentMode
from deeplearning4j_tpu.datavec.transform import (
    CategoricalToInteger, ConditionOp, MathOp, MinMaxNormalize,
    StandardizeNormalize,
)


# --------------------------------------------------------------------------
# splits
# --------------------------------------------------------------------------
def test_file_split_filters_and_recurses(tmp_path):
    (tmp_path / "a").mkdir()
    (tmp_path / "a" / "x.csv").write_text("1,2\n")
    (tmp_path / "a" / "y.txt").write_text("no")
    (tmp_path / "z.csv").write_text("3,4\n")
    locs = FileSplit(tmp_path, allowed_extensions=["csv"]).locations()
    assert [l.split("/")[-1] for l in locs] == ["x.csv", "z.csv"]


def test_numbered_file_split():
    s = NumberedFileInputSplit("seq_%d.csv", 0, 2)
    assert s.locations() == ["seq_0.csv", "seq_1.csv", "seq_2.csv"]
    with pytest.raises(ValueError):
        NumberedFileInputSplit("nopattern.csv", 0, 1)


# --------------------------------------------------------------------------
# readers
# --------------------------------------------------------------------------
def test_csv_record_reader(tmp_path):
    f = tmp_path / "data.csv"
    f.write_text("h1,h2\n1,2\n3,4\n")
    rr = CSVRecordReader(skip_num_lines=1).initialize(FileSplit(f))
    assert list(rr) == [["1", "2"], ["3", "4"]]


def test_csv_reader_string_split():
    rr = CSVRecordReader().initialize(StringSplit("5,6\n7,8"))
    assert list(rr) == [["5", "6"], ["7", "8"]]


def test_line_and_regex_readers(tmp_path):
    f = tmp_path / "log.txt"
    f.write_text("INFO 100\nWARN 200\n")
    assert list(LineRecordReader().initialize(FileSplit(f))) == [
        ["INFO 100"], ["WARN 200"]]
    rr = RegexLineRecordReader(r"(\w+) (\d+)").initialize(FileSplit(f))
    assert list(rr) == [["INFO", "100"], ["WARN", "200"]]


def test_json_record_reader(tmp_path):
    f = tmp_path / "data.jsonl"
    f.write_text('{"a": 1, "b": "x"}\n{"a": 2, "b": "y"}\n')
    rr = JsonRecordReader(["b", "a"]).initialize(FileSplit(f))
    assert list(rr) == [["x", 1], ["y", 2]]


def test_csv_sequence_reader(tmp_path):
    for i in range(2):
        (tmp_path / f"seq_{i}.csv").write_text(f"{i},0\n{i},1\n")
    rr = CSVSequenceRecordReader().initialize(
        NumberedFileInputSplit(str(tmp_path / "seq_%d.csv"), 0, 1))
    seqs = list(rr)
    assert seqs[0] == [["0", "0"], ["0", "1"]]
    assert seqs[1] == [["1", "0"], ["1", "1"]]


# --------------------------------------------------------------------------
# schema + transform process
# --------------------------------------------------------------------------
def _schema():
    return (Schema.builder()
            .add_column_string("name")
            .add_column_categorical("color", ["red", "green", "blue"])
            .add_column_double("value")
            .build())


def test_schema_json_roundtrip():
    s = _schema()
    s2 = Schema.from_json(s.to_json())
    assert s2 == s
    assert s2.index_of("value") == 2


def test_transform_process_chain_and_roundtrip():
    tp = (TransformProcess.builder(_schema())
          .remove_columns("name")
          .categorical_to_integer("color")
          .math_op("value", MathOp.Multiply, 2.0)
          .filter_condition("value", ConditionOp.GreaterThan, 10.0)
          .build())
    out = tp.execute([["a", "red", "3.0"], ["b", "blue", "7.0"]])
    # 3*2=6 kept, 7*2=14 filtered (condition true => removed)
    assert out == [[0, 6.0]]
    tp2 = TransformProcess.from_json(tp.to_json())
    assert tp2.execute([["a", "green", "4.0"]]) == [[1, 8.0]]
    final = tp.final_schema()
    assert final.names() == ["color", "value"]


def test_one_hot_and_normalize():
    tp = (TransformProcess.builder(_schema())
          .remove_columns("name")
          .categorical_to_one_hot("color")
          .normalize(MinMaxNormalize("value", 0.0, 10.0))
          .build())
    out = tp.execute_record(["x", "green", 5.0])
    assert out == [0, 1, 0, 0.5]
    assert tp.final_schema().names() == [
        "color[red]", "color[green]", "color[blue]", "value"]


def test_fit_normalizers():
    schema = Schema.builder().add_column_double("v").build()
    records = [[1.0], [2.0], [3.0]]
    (norm,) = TransformProcess.fit_normalizers(schema, records, ["v"],
                                               kind="standardize")
    assert isinstance(norm, StandardizeNormalize)
    assert norm.mean == pytest.approx(2.0)
    out = [norm.map_record(schema, r)[0] for r in records]
    assert np.mean(out) == pytest.approx(0.0)


def test_transform_process_record_reader():
    tp = (TransformProcess.builder(_schema())
          .remove_columns("name")
          .categorical_to_integer("color")
          .build())
    rr = CollectionRecordReader([["a", "red", 1.0], ["b", "blue", 2.0]])
    wrapped = TransformProcessRecordReader(rr, tp)
    wrapped.initialize(None)
    assert list(wrapped) == [[0, 1.0], [2, 2.0]]


# --------------------------------------------------------------------------
# dataset bridge
# --------------------------------------------------------------------------
def test_rr_dataset_iterator_classification():
    rr = CollectionRecordReader([[0.1, 0.2, 0], [0.3, 0.4, 1],
                                 [0.5, 0.6, 2]])
    it = RecordReaderDataSetIterator(rr, batch_size=2, label_index=2,
                                     num_possible_labels=3)
    batches = list(it)
    assert batches[0].features.shape == (2, 2)
    assert batches[0].labels.shape == (2, 3)
    np.testing.assert_allclose(batches[0].labels[1], [0, 1, 0])
    assert batches[1].features.shape == (1, 2)


def test_rr_dataset_iterator_regression_range():
    rr = CollectionRecordReader([[1.0, 2.0, 3.0, 4.0]])
    it = RecordReaderDataSetIterator(rr, batch_size=1, label_index=2,
                                     label_index_to=3, regression=True)
    (ds,) = list(it)
    np.testing.assert_allclose(ds.features, [[1.0, 2.0]])
    np.testing.assert_allclose(ds.labels, [[3.0, 4.0]])


def test_sequence_iterator_masking_align_start_end():
    seqs = [
        [[0.0, 1.0, 0], [0.1, 1.1, 1]],                    # len 2
        [[0.2, 1.2, 1], [0.3, 1.3, 0], [0.4, 1.4, 1]],     # len 3
    ]
    rr = CollectionSequenceRecordReader(seqs)
    it = SequenceRecordReaderDataSetIterator(
        rr, batch_size=2, label_index=2, num_possible_labels=2)
    (ds,) = list(it)
    assert ds.features.shape == (2, 3, 2)   # [batch, time, feat]
    assert ds.labels.shape == (2, 3, 2)
    np.testing.assert_allclose(ds.labels_mask, [[1, 1, 0], [1, 1, 1]])
    np.testing.assert_allclose(ds.features[0, 1], [0.1, 1.1])
    # ALIGN_END pads at the front
    rr2 = CollectionSequenceRecordReader(seqs)
    it2 = SequenceRecordReaderDataSetIterator(
        rr2, batch_size=2, label_index=2, num_possible_labels=2,
        alignment=AlignmentMode.ALIGN_END)
    (ds2,) = list(it2)
    np.testing.assert_allclose(ds2.labels_mask, [[0, 1, 1], [1, 1, 1]])
    np.testing.assert_allclose(ds2.features[0, 1], [0.0, 1.0])


def test_sequence_iterator_channels_first():
    seqs = [[[0.0, 1.0, 0], [0.1, 1.1, 1]]]
    rr = CollectionSequenceRecordReader(seqs)
    it = SequenceRecordReaderDataSetIterator(
        rr, batch_size=1, label_index=2, num_possible_labels=2,
        channels_first=True)
    (ds,) = list(it)
    assert ds.features.shape == (1, 2, 2)
    np.testing.assert_allclose(ds.features[0, :, 1], [0.1, 1.1])


def test_condition_equal_coerces_csv_strings():
    # CSV cells are strings; Equal/InSet must match numeric condition values
    schema = Schema.builder().add_column_integer("age").build()
    tp = (TransformProcess.builder(schema)
          .filter_condition("age", ConditionOp.Equal, 30)
          .build())
    assert tp.execute([["30"], ["31"]]) == [["31"]]
    tp2 = (TransformProcess.builder(schema)
           .filter_condition("age", ConditionOp.InSet, [30, 40])
           .build())
    assert tp2.execute([["30"], ["35"], ["40"]]) == [["35"]]


def test_transform_reader_reset_delegates():
    class CountingReader(CollectionRecordReader):
        def __init__(self):
            super().__init__([[1.0]])
            self.resets = 0

        def reset(self):
            self.resets += 1

    inner = CountingReader()
    tp = TransformProcess.builder(
        Schema.builder().add_column_double("v").build()).build()
    wrapped = TransformProcessRecordReader(inner, tp)
    wrapped.reset()
    assert inner.resets == 1


# --------------------------------------------------------------------------
# audio (reference datavec-data-audio: WavFileRecordReader, spectrogram,
# MFCC features)
# --------------------------------------------------------------------------

def _write_wav(path, samples, rate=8000, width=2, channels=1):
    import wave

    with wave.open(str(path), "wb") as f:
        f.setnchannels(channels)
        f.setsampwidth(width)
        f.setframerate(rate)
        if width == 2:
            data = (np.clip(samples, -1, 1) * 32767).astype("<i2")
        elif width == 1:
            data = ((np.clip(samples, -1, 1) * 127) + 128).astype(np.uint8)
        else:
            data = (np.clip(samples, -1, 1) * (2**31 - 1)).astype("<i4")
        if channels > 1:
            data = np.repeat(data[:, None], channels, axis=1)
        f.writeframes(data.tobytes())


def test_wav_reader_roundtrip(tmp_path):
    from deeplearning4j_tpu.datavec.audio import WavFileRecordReader, read_wav
    from deeplearning4j_tpu.datavec.split import FileSplit

    t = np.arange(800) / 8000.0
    sig = 0.5 * np.sin(2 * np.pi * 440.0 * t)
    for label in ("dog", "cat"):
        d = tmp_path / label
        d.mkdir()
        _write_wav(d / "a.wav", sig)
    x, rate = read_wav(str(tmp_path / "dog" / "a.wav"))
    assert rate == 8000 and x.shape == (800,)
    np.testing.assert_allclose(x, sig, atol=2e-4)

    rr = WavFileRecordReader(label_from_parent_dir=True).initialize(
        FileSplit(tmp_path, allowed_extensions=["wav"]))
    recs = list(rr)
    assert len(recs) == 2
    assert rr.labels() == ["cat", "dog"]
    waveform, rate2, label_idx = recs[0]
    assert rate2 == 8000 and label_idx in (0, 1)

    # 8-bit and stereo decode paths
    _write_wav(tmp_path / "w8.wav", sig, width=1)
    x8, _ = read_wav(str(tmp_path / "w8.wav"))
    np.testing.assert_allclose(x8, sig, atol=2e-2)
    _write_wav(tmp_path / "st.wav", sig, channels=2)
    xs, _ = read_wav(str(tmp_path / "st.wav"))
    assert xs.shape == (800,)


def test_spectrogram_peak_and_mfcc_shape():
    from deeplearning4j_tpu.datavec.audio import mfcc, spectrogram

    rate, freq = 8000.0, 1000.0
    t = np.arange(4096) / rate
    sig = np.sin(2 * np.pi * freq * t).astype(np.float32)
    spec = spectrogram(sig, frame_length=256)
    assert spec.shape[1] == 129
    # energy peaks at the sine's bin: 1000/8000*256 = bin 32
    assert int(np.argmax(spec.mean(axis=0))) == 32

    feats = mfcc(sig, rate, n_mfcc=13)
    assert feats.shape[1] == 13
    assert np.isfinite(feats).all()
    # MFCCs of a pure tone differ from white noise
    noise = np.random.default_rng(0).normal(size=4096).astype(np.float32)
    f_noise = mfcc(noise, rate, n_mfcc=13)
    assert np.abs(feats.mean(0) - f_noise.mean(0)).max() > 1.0


# --- round 2: joins (reference org.datavec.api.transform.join.Join) --------

def _join_schemas():
    from deeplearning4j_tpu.datavec.schema import SchemaBuilder

    left = (SchemaBuilder().add_column_string("id")
            .add_column_integer("age").build())
    right = (SchemaBuilder().add_column_string("id")
             .add_column_string("city").build())
    return left, right


def test_inner_join():
    from deeplearning4j_tpu.datavec import Join, JoinType

    left, right = _join_schemas()
    j = (Join.Builder(JoinType.INNER)
         .set_join_columns("id").set_schemas(left, right).build())
    out_schema = j.output_schema()
    assert [c.name for c in out_schema.columns] == ["id", "age", "city"]
    lrows = [["a", 30], ["b", 25], ["c", 40]]
    rrows = [["a", "paris"], ["c", "rome"], ["d", "oslo"]]
    got = j.execute(lrows, rrows)
    assert sorted(map(tuple, got)) == [("a", 30, "paris"), ("c", 40, "rome")]


def test_left_right_full_outer_joins():
    from deeplearning4j_tpu.datavec import Join, JoinType

    left, right = _join_schemas()
    lrows = [["a", 30], ["b", 25]]
    rrows = [["a", "paris"], ["d", "oslo"]]

    def run(t):
        return sorted(map(tuple, Join.Builder(t).set_join_columns("id")
                          .set_schemas(left, right).build()
                          .execute(lrows, rrows)))

    assert run(JoinType.LEFT_OUTER) == [("a", 30, "paris"), ("b", 25, None)]
    assert run(JoinType.RIGHT_OUTER) == [("a", 30, "paris"),
                                         ("d", None, "oslo")]
    assert run(JoinType.FULL_OUTER) == [("a", 30, "paris"), ("b", 25, None),
                                        ("d", None, "oslo")]


def test_join_duplicate_keys_cartesian_and_renamed_right_key():
    from deeplearning4j_tpu.datavec import Join, JoinType
    from deeplearning4j_tpu.datavec.schema import SchemaBuilder

    left = (SchemaBuilder().add_column_string("k")
            .add_column_integer("v").build())
    right = (SchemaBuilder().add_column_string("rk")
             .add_column_integer("w").build())
    j = (Join.Builder(JoinType.INNER).set_join_columns("k")
         .set_join_columns_right("rk").set_schemas(left, right).build())
    got = j.execute([["x", 1], ["x", 2]], [["x", 10], ["x", 20]])
    assert sorted(map(tuple, got)) == [
        ("x", 1, 10), ("x", 1, 20), ("x", 2, 10), ("x", 2, 20)]


def test_join_validates_columns():
    from deeplearning4j_tpu.datavec import Join, JoinType
    from deeplearning4j_tpu.datavec.schema import SchemaBuilder

    left, right = _join_schemas()
    with pytest.raises(KeyError):
        (Join.Builder(JoinType.INNER).set_join_columns("nope")
         .set_schemas(left, right).build())
    # colliding non-key names must be rejected
    l2 = (SchemaBuilder().add_column_string("id")
          .add_column_integer("x").build())
    r2 = (SchemaBuilder().add_column_string("id")
          .add_column_integer("x").build())
    with pytest.raises(ValueError, match="both sides"):
        (Join.Builder(JoinType.INNER).set_join_columns("id")
         .set_schemas(l2, r2).build())
