"""Config JSON round-trip tests (reference: heavily-tested Jackson round
trips of MultiLayerConfiguration / updater / loss configs, SURVEY.md §5.6)."""

from deeplearning4j_tpu import serde
from deeplearning4j_tpu.conf.activations import Activation
from deeplearning4j_tpu.conf.inputs import InputType
from deeplearning4j_tpu.conf.losses import LossBinaryXENT, LossMCXENT, LossMSE
from deeplearning4j_tpu.conf.regularization import (
    L1Regularization,
    L2Regularization,
    WeightDecay,
)
from deeplearning4j_tpu.conf.schedules import (
    CycleSchedule,
    ExponentialSchedule,
    FixedSchedule,
    InverseSchedule,
    MapSchedule,
    PolySchedule,
    ScheduleType,
    SigmoidSchedule,
    StepSchedule,
    WarmupSchedule,
)
from deeplearning4j_tpu.conf.updaters import (
    AMSGrad,
    AdaDelta,
    AdaGrad,
    AdaMax,
    Adam,
    AdamW,
    Nadam,
    Nesterovs,
    NoOp,
    RmsProp,
    Sgd,
)
from deeplearning4j_tpu.conf.weights import Distribution, WeightInit


def roundtrip(obj):
    restored = serde.from_json(serde.to_json(obj))
    assert restored == obj, f"{obj} != {restored}"
    return restored


def test_updater_roundtrip():
    for u in [
        Sgd(learning_rate=0.05),
        Adam(learning_rate=3e-4, beta1=0.85),
        AdamW(weight_decay=0.02),
        AMSGrad(),
        AdaMax(),
        Nadam(),
        Nesterovs(momentum=0.95),
        AdaGrad(),
        AdaDelta(rho=0.9),
        RmsProp(rms_decay=0.9),
        NoOp(),
        Adam(lr_schedule=StepSchedule(initial_value=0.01, step=500)),
    ]:
        roundtrip(u)


def test_schedule_roundtrip():
    for s in [
        FixedSchedule(0.01),
        StepSchedule(ScheduleType.EPOCH, 0.1, 0.5, 10),
        ExponentialSchedule(gamma=0.97),
        InverseSchedule(power=0.75),
        PolySchedule(max_iter=5000),
        SigmoidSchedule(step_size=300),
        MapSchedule(values={"0": 0.1, "100": 0.01}),
        CycleSchedule(cycle_length=2000),
        WarmupSchedule(warmup_steps=50, inner=ExponentialSchedule()),
    ]:
        roundtrip(s)


def test_loss_and_misc_roundtrip():
    roundtrip(LossMSE(weights=(0.5, 1.0, 2.0)))
    roundtrip(LossMCXENT())
    roundtrip(LossBinaryXENT(clip_eps=1e-6))
    roundtrip(L1Regularization(l1=1e-4))
    roundtrip(L2Regularization(l2=5e-4))
    roundtrip(WeightDecay(coeff=0.01, apply_lr=False))
    roundtrip(Distribution(kind="uniform", lower=-0.1, upper=0.1))
    roundtrip(InputType.convolutional(28, 28, 1))
    roundtrip(InputType.recurrent(128, 50))


def test_enum_roundtrip():
    assert serde.from_json(serde.to_json(Activation.SOFTMAX)) is Activation.SOFTMAX
    assert serde.from_json(serde.to_json(WeightInit.XAVIER)) is WeightInit.XAVIER


def test_unknown_field_rejected():
    import pytest

    bad = '{"@type": "Sgd", "learning_rate": 0.1, "bogus": 1}'
    with pytest.raises(ValueError):
        serde.from_json(bad)


def test_unregistered_subclass_rejected():
    import dataclasses

    import pytest

    @dataclasses.dataclass
    class SneakySgd(Sgd):  # NOT @serde.register-ed
        extra: float = 1.0

    with pytest.raises(TypeError):
        serde.to_json(SneakySgd())


def test_hardsigmoid_matches_reference_form():
    import jax.numpy as jnp
    import numpy as np

    x = jnp.asarray([-3.0, -2.5, 0.0, 1.0, 2.5, 3.0])
    got = np.asarray(Activation.HARDSIGMOID.apply(x))
    want = np.clip(0.2 * np.asarray(x) + 0.5, 0.0, 1.0)
    np.testing.assert_allclose(got, want, rtol=1e-6)
