"""ParallelWrapper / ParallelInference over an 8-virtual-device CPU mesh
(reference oracle: deeplearning4j-scaleout-parallelwrapper tests run N
workers on CPU threads — SURVEY.md §4 'Multi-device w/o real cluster')."""

import numpy as np
import pytest

from deeplearning4j_tpu.conf import Activation, InputType, WeightInit
from deeplearning4j_tpu.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.conf.losses import LossMCXENT
from deeplearning4j_tpu.conf.multilayer import NeuralNetConfiguration
from deeplearning4j_tpu.conf.updaters import Adam, Sgd
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import ArrayDataSetIterator
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel import (
    AdaptiveThresholdAlgorithm,
    ParallelInference,
    ParallelWrapper,
    ThresholdAlgorithm,
    TrainingMode,
    single_host_mesh,
)


def _data(n=64, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 4)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, n)]
    return x, y


def _conf(updater=None, seed=12345):
    return (NeuralNetConfiguration.builder()
            .seed(seed)
            .updater(updater or Adam(learning_rate=0.05))
            .weight_init(WeightInit.XAVIER)
            .list()
            .layer(DenseLayer(n_out=16, activation=Activation.TANH))
            .layer(OutputLayer(n_out=3, activation=Activation.SOFTMAX,
                               loss_fn=LossMCXENT()))
            .set_input_type(InputType.feed_forward(4))
            .build())


def test_mesh_has_8_devices():
    mesh = single_host_mesh()
    assert mesh.shape["data"] == 8


def test_shared_gradients_exact_matches_single_device():
    """Exact (uncompressed) gradient sharing == single-device training on
    the same global batch: the all-reduced mean gradient is the full-batch
    gradient (the reference's lossless-accumulator limit)."""
    x, y = _data(64)
    serial = MultiLayerNetwork(_conf()).init()
    par = MultiLayerNetwork(_conf()).init()

    pw = ParallelWrapper(par, training_mode=TrainingMode.SHARED_GRADIENTS)
    it = ArrayDataSetIterator(x, y, batch=64)
    for _ in range(3):
        serial.fit_batch(DataSet(x, y))
    pw.fit(it, epochs=3)

    for k in serial.params:
        for pk in serial.params[k]:
            np.testing.assert_allclose(
                np.asarray(serial.params[k][pk]),
                np.asarray(par.params[k][pk]), atol=2e-5,
                err_msg=f"layer {k} param {pk}")


def test_shared_gradients_ragged_batch():
    """Batch not divisible by 8: padded rows must not change the math."""
    x, y = _data(64)
    serial = MultiLayerNetwork(_conf()).init()
    par = MultiLayerNetwork(_conf()).init()
    pw = ParallelWrapper(par)
    # 61 rows -> padded to 64 with zero label-mask
    serial.fit_batch(DataSet(x[:61], y[:61]))
    pw.fit(ArrayDataSetIterator(x[:61], y[:61], batch=61), epochs=1)
    for k in serial.params:
        for pk in serial.params[k]:
            np.testing.assert_allclose(
                np.asarray(serial.params[k][pk]),
                np.asarray(par.params[k][pk]), atol=2e-5)


def test_averaging_freq1_sgd_matches_full_batch():
    """With plain SGD and averaging every iteration, averaged replica params
    equal a single full-batch step: mean_i(p - lr*g_i) = p - lr*mean(g_i).
    (Reference AVERAGING mode semantics.)"""
    x, y = _data(64)
    serial = MultiLayerNetwork(_conf(Sgd(learning_rate=0.1))).init()
    par = MultiLayerNetwork(_conf(Sgd(learning_rate=0.1))).init()
    pw = ParallelWrapper(par, training_mode=TrainingMode.AVERAGING,
                         averaging_frequency=1)
    serial.fit_batch(DataSet(x, y))
    pw.fit(ArrayDataSetIterator(x, y, batch=64), epochs=1)
    for k in serial.params:
        for pk in serial.params[k]:
            np.testing.assert_allclose(
                np.asarray(serial.params[k][pk]),
                np.asarray(par.params[k][pk]), atol=2e-5)


def test_averaging_periodic_converges():
    x, y = _data(256, seed=1)
    net2 = MultiLayerNetwork(_conf()).init()
    pw2 = ParallelWrapper(net2, training_mode=TrainingMode.AVERAGING,
                          averaging_frequency=3)
    scores = []
    orig = pw2._fit_batch

    def spy(ds):
        orig(ds)
        scores.append(pw2.score_value)

    pw2._fit_batch = spy
    pw2.fit(ArrayDataSetIterator(x, y, batch=64), epochs=8)
    assert scores[-1] < scores[0]
    assert np.isfinite(scores[-1])


def test_threshold_shared_gradients_converges():
    """Compressed mode: residual-corrected ±tau exchange still trains."""
    x, y = _data(256, seed=2)
    # sign-magnitude exchange: per-step movement is bounded by
    # workers*tau*lr, so pick tau/lr in the regime the reference tunes for
    net = MultiLayerNetwork(_conf(Sgd(learning_rate=0.5))).init()
    pw = ParallelWrapper(
        net, training_mode=TrainingMode.SHARED_GRADIENTS,
        threshold_algorithm=ThresholdAlgorithm(threshold=1e-2))
    scores = []
    orig = pw._fit_batch

    def spy(ds):
        orig(ds)
        scores.append(pw.score_value)

    pw._fit_batch = spy
    pw.fit(ArrayDataSetIterator(x, y, batch=64), epochs=10)
    assert scores[-1] < scores[0]


def test_adaptive_threshold_updates_tau():
    x, y = _data(64, seed=3)
    net = MultiLayerNetwork(_conf()).init()
    algo = AdaptiveThresholdAlgorithm(threshold=1e-2)
    pw = ParallelWrapper(net, threshold_algorithm=algo)
    pw.fit(ArrayDataSetIterator(x, y, batch=64), epochs=3)
    assert pw._tau > 0
    assert np.isfinite(pw._tau)


def test_parallel_inference_matches_serial():
    x, y = _data(13, seed=4)  # ragged on purpose
    net = MultiLayerNetwork(_conf()).init()
    expected = np.asarray(net.output(x))
    pi = ParallelInference(net)
    got = pi.output(x)
    np.testing.assert_allclose(got, expected, atol=1e-6)
    assert got.shape == (13, 3)


def test_parallel_inference_batch_limit():
    x, _ = _data(40, seed=5)
    net = MultiLayerNetwork(_conf()).init()
    expected = np.asarray(net.output(x))
    pi = ParallelInference(net, batch_limit=16)
    got = pi.output(x)
    np.testing.assert_allclose(got, expected, atol=1e-6)


def test_graph_parallel_wrapper():
    """ComputationGraph under the wrapper (exact mode)."""
    from deeplearning4j_tpu.conf.graph import ComputationGraphConfiguration
    from deeplearning4j_tpu.nn.graph import ComputationGraph

    x, y = _data(64, seed=6)
    conf = (NeuralNetConfiguration.builder()
            .seed(12345)
            .updater(Adam(learning_rate=0.05))
            .weight_init(WeightInit.XAVIER)
            .graph_builder()
            .add_inputs("in")
            .add_layer("d", DenseLayer(n_out=16, activation=Activation.TANH),
                       "in")
            .add_layer("out", OutputLayer(n_out=3,
                                          activation=Activation.SOFTMAX,
                                          loss_fn=LossMCXENT()), "d")
            .set_outputs("out")
            .set_input_types(InputType.feed_forward(4))
            .build())
    serial = ComputationGraph(conf).init()
    par = ComputationGraph(
        ComputationGraphConfiguration.from_json(conf.to_json())).init()
    serial.fit_batch(DataSet(x, y))
    pw = ParallelWrapper(par)
    pw.fit(DataSet(x, y), epochs=1)
    for k in serial.params:
        for pk in serial.params[k]:
            np.testing.assert_allclose(
                np.asarray(serial.params[k][pk]),
                np.asarray(par.params[k][pk]), atol=2e-5)


# --------------------------------------------------------------------------
# tensor parallelism (beyond reference parity: Megatron-style TP block)
# --------------------------------------------------------------------------

def test_tensor_parallel_block_matches_single_device():
    import jax
    from jax.sharding import Mesh

    from deeplearning4j_tpu.parallel.tensor import (
        shard_tp_params,
        tp_block_apply,
        tp_block_init,
        tp_train_step,
    )

    D, H, F, B, T = 16, 4, 32, 4, 6
    params = tp_block_init(jax.random.PRNGKey(0), D, H, F)
    x = jax.random.normal(jax.random.PRNGKey(1), (B, T, D))

    # single-logical-device reference
    want = tp_block_apply(params, x, H)

    devices = np.array(jax.devices()[:8]).reshape(4, 2)
    mesh = Mesh(devices, ("data", "model"))
    sharded = shard_tp_params(params, mesh)
    # weights really live sharded over the model axis
    spec = sharded["w_qkv"].sharding.spec
    assert "model" in str(spec)
    with mesh:
        got = jax.jit(lambda p, x: tp_block_apply(p, x, H, mesh))(sharded, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)

    # training step: loss decreases, params stay sharded
    tgt = jax.random.normal(jax.random.PRNGKey(2), (B, T, D))
    step = tp_train_step(mesh, H, lr=0.05)
    with mesh:
        p, l0 = step(sharded, x, tgt)
        for _ in range(10):
            p, loss = step(p, x, tgt)
    assert float(loss) < float(l0)
    assert "model" in str(p["w_ff1"].sharding.spec)


def test_inference_server_http():
    import json
    import urllib.error
    import urllib.request

    import numpy as np

    from deeplearning4j_tpu.conf import Activation, InputType
    from deeplearning4j_tpu.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.conf.losses import LossMCXENT
    from deeplearning4j_tpu.conf.multilayer import NeuralNetConfiguration
    from deeplearning4j_tpu.conf.updaters import Sgd
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.parallel import InferenceServer

    conf = (NeuralNetConfiguration.builder().seed(0).updater(Sgd(0.1)).list()
            .layer(DenseLayer(n_out=8, activation=Activation.TANH))
            .layer(OutputLayer(n_out=3, activation=Activation.SOFTMAX,
                               loss_fn=LossMCXENT()))
            .set_input_type(InputType.feed_forward(4)).build())
    net = MultiLayerNetwork(conf).init()
    server = InferenceServer(net).start(port=0)
    try:
        base = f"http://127.0.0.1:{server.port}"
        health = json.loads(urllib.request.urlopen(
            base + "/healthz", timeout=10).read())
        assert health["status"] == "ok"
        info = json.loads(urllib.request.urlopen(
            base + "/model", timeout=10).read())
        assert info["type"] == "MultiLayerNetwork"

        x = np.random.default_rng(0).normal(size=(5, 4)).astype(np.float32)
        req = urllib.request.Request(
            base + "/predict",
            data=json.dumps({"inputs": [x.tolist()]}).encode(),
            headers={"Content-Type": "application/json"})
        resp = json.loads(urllib.request.urlopen(req, timeout=30).read())
        got = np.asarray(resp["outputs"][0])
        want = np.asarray(net.output(x))
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

        # malformed request -> 400 with an error message
        bad = urllib.request.Request(
            base + "/predict", data=b'{"nope": 1}',
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(bad, timeout=10)
        assert ei.value.code == 400
    finally:
        server.stop()


def test_inference_server_500_on_model_failure():
    import json
    import urllib.error
    import urllib.request

    from deeplearning4j_tpu.parallel import InferenceServer

    class Broken:
        params = {}

        def output(self, *xs):
            raise RuntimeError("device exploded")

    server = InferenceServer(Broken()).start(port=0)
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}/predict",
            data=json.dumps({"inputs": [[1.0]]}).encode(),
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=10)
        assert ei.value.code == 500
        assert "device exploded" in ei.value.read().decode()
    finally:
        server.stop()


def test_tp_block_init_validates_heads():
    import jax

    from deeplearning4j_tpu.parallel import tp_block_init

    with pytest.raises(ValueError, match="divisible"):
        tp_block_init(jax.random.PRNGKey(0), 16, 3, 64)


def test_inference_server_input_validation():
    import json
    import urllib.error
    import urllib.request

    import numpy as np

    from deeplearning4j_tpu.conf import Activation, InputType
    from deeplearning4j_tpu.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.conf.losses import LossMCXENT
    from deeplearning4j_tpu.conf.multilayer import NeuralNetConfiguration
    from deeplearning4j_tpu.conf.updaters import Sgd
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.parallel import InferenceServer

    conf = (NeuralNetConfiguration.builder().seed(0).updater(Sgd(0.1)).list()
            .layer(DenseLayer(n_out=4, activation=Activation.TANH))
            .layer(OutputLayer(n_out=2, activation=Activation.SOFTMAX,
                               loss_fn=LossMCXENT()))
            .set_input_type(InputType.feed_forward(3)).build())
    server = InferenceServer(MultiLayerNetwork(conf).init()).start(port=0)
    try:
        base = f"http://127.0.0.1:{server.port}/predict"
        x = [[1.0, 2.0, 3.0]]
        for payload, match in [
                ({"inputs": [x, x]}, "takes 1 input"),   # wrong arity
                ({"inputs": [[[1.0, 2.0], [3.0]]]}, "malformed")]:  # ragged
            req = urllib.request.Request(
                base, data=json.dumps(payload).encode(),
                headers={"Content-Type": "application/json"})
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req, timeout=10)
            assert ei.value.code == 400
            assert match in ei.value.read().decode()
    finally:
        server.stop()


# --- tBPTT under the wrapper (round 2: SURVEY §3.4 + §5.7) -----------------

def _rnn_conf(seed=12345, updater=None):
    from deeplearning4j_tpu.conf.layers_rnn import LSTM, RnnOutputLayer
    from deeplearning4j_tpu.conf.multilayer import BackpropType

    return (NeuralNetConfiguration.builder()
            .seed(seed)
            .updater(updater or Adam(learning_rate=0.02))
            .weight_init(WeightInit.XAVIER)
            .list()
            .layer(LSTM(n_out=12))
            .layer(RnnOutputLayer(n_out=3, activation=Activation.SOFTMAX,
                                  loss_fn=LossMCXENT()))
            .backprop_type(BackpropType.TRUNCATED_BPTT, fwd=5, back=5)
            .set_input_type(InputType.recurrent(4, 20))
            .build())


def _rnn_data(n=16, t=20, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, t, 4)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, (n, t))]
    return x, y


def test_tbptt_shared_gradients_exact_matches_single_device():
    """tBPTT under the wrapper (exact mode, 8-device mesh) == the
    single-device compiled segment scan on the same global batch."""
    x, y = _rnn_data(16)
    serial = MultiLayerNetwork(_rnn_conf()).init()
    par = MultiLayerNetwork(_rnn_conf()).init()

    pw = ParallelWrapper(par, training_mode=TrainingMode.SHARED_GRADIENTS)
    for _ in range(2):
        serial.fit_batch(DataSet(x, y))
    it = ArrayDataSetIterator(x, y, batch=16)
    pw.fit(it, epochs=2)

    assert par.iteration == serial.iteration == 8  # 2 batches x 4 segments
    for k in serial.params:
        for pk in serial.params[k]:
            np.testing.assert_allclose(
                np.asarray(serial.params[k][pk]),
                np.asarray(par.params[k][pk]), atol=3e-5,
                err_msg=f"layer {k} param {pk}")


def test_tbptt_shared_gradients_ragged_batch():
    """13 rows over 8 workers: padded rows carry zero masks end-to-end."""
    x, y = _rnn_data(13, seed=3)
    serial = MultiLayerNetwork(_rnn_conf()).init()
    par = MultiLayerNetwork(_rnn_conf()).init()
    pw = ParallelWrapper(par)
    serial.fit_batch(DataSet(x, y))
    pw.fit(ArrayDataSetIterator(x, y, batch=13), epochs=1)
    for k in serial.params:
        for pk in serial.params[k]:
            np.testing.assert_allclose(
                np.asarray(serial.params[k][pk]),
                np.asarray(par.params[k][pk]), atol=3e-5)


def test_tbptt_averaging_converges():
    """AVERAGING mode with tBPTT: loss decreases and final params are
    finite (replicas run independent local segment scans, then average)."""
    x, y = _rnn_data(16, seed=5)
    par = MultiLayerNetwork(_rnn_conf(seed=7)).init()
    pw = ParallelWrapper(par, training_mode=TrainingMode.AVERAGING,
                         averaging_frequency=4)
    it = ArrayDataSetIterator(x, y, batch=16)
    pw.fit(it, epochs=1)
    first = pw.score_value
    pw.fit(it, epochs=4)
    assert np.isfinite(pw.score_value)
    assert pw.score_value < first
    flat = par.params_flat()
    assert np.all(np.isfinite(flat))


def test_tbptt_back_lt_fwd_exact_matches_single_device():
    """back < fwd (state-advance head + short backprop window) under the
    wrapper == the single-device compiled path on the same batch."""
    from deeplearning4j_tpu.conf.layers_rnn import LSTM, RnnOutputLayer
    from deeplearning4j_tpu.conf.multilayer import BackpropType

    def conf():
        return (NeuralNetConfiguration.builder()
                .seed(5).updater(Adam(learning_rate=0.02))
                .weight_init(WeightInit.XAVIER)
                .list()
                .layer(LSTM(n_out=8))
                .layer(RnnOutputLayer(n_out=3, activation=Activation.SOFTMAX,
                                      loss_fn=LossMCXENT()))
                .backprop_type(BackpropType.TRUNCATED_BPTT, fwd=5, back=3)
                .set_input_type(InputType.recurrent(4, 20))
                .build())

    x, y = _rnn_data(16, seed=9)
    serial = MultiLayerNetwork(conf()).init()
    par = MultiLayerNetwork(conf()).init()
    serial.fit_batch(DataSet(x, y))
    ParallelWrapper(par).fit(ArrayDataSetIterator(x, y, batch=16), epochs=1)
    for k in serial.params:
        for pk in serial.params[k]:
            np.testing.assert_allclose(
                np.asarray(serial.params[k][pk]),
                np.asarray(par.params[k][pk]), atol=3e-5,
                err_msg=f"layer {k} param {pk}")


def test_weak_scaling_no_serialization():
    """Weak scaling (fixed per-device batch): the sharded step must not
    serialize across the data axis — step time at 8 devices stays within
    2x of 1 device (virtual CPU devices share host cores, so anything
    near-flat means the compiled program parallelizes; a serialized step
    would scale ~8x). BASELINE.md records the measured table."""
    import time

    import jax

    from deeplearning4j_tpu.conf.updaters import Sgd
    from deeplearning4j_tpu.parallel import MeshConfig

    def step_time(n, per_dev=8, steps=6, repeats=3):
        serial_conf = _conf(Sgd(learning_rate=0.05))
        net = MultiLayerNetwork(serial_conf).init()
        mesh = MeshConfig(devices=list(jax.devices()[:n])).build()
        pw = ParallelWrapper(net, mesh=mesh, prefetch_buffer=0)
        x, y = _data(per_dev * n)
        ds = DataSet(x, y)
        pw.fit(ds, epochs=2)  # compile + warm
        best = float("inf")
        for _ in range(repeats):  # min-of-repeats: robust to host noise
            t0 = time.perf_counter()
            pw.fit(ds, epochs=steps)
            best = min(best, (time.perf_counter() - t0) / steps)
        return best

    t1 = step_time(1)
    t8 = step_time(8)
    # a serialized step would scale ~8x; 3x leaves generous headroom for
    # shared-core contention on loaded CI hosts (measured ratio ~1.0)
    assert t8 < 3.0 * t1 + 0.05, (
        f"sharded step appears serialized: {t1*1e3:.1f}ms @1 dev vs "
        f"{t8*1e3:.1f}ms @8 devs")


def test_tbptt_threshold_shared_gradients_converges():
    """Threshold-compressed gradient exchange per tBPTT SEGMENT (the
    reference exchanges every iteration; tBPTT counts one per segment):
    residual-corrected ±tau training reduces the loss."""
    x, y = _rnn_data(16, seed=11)
    par = MultiLayerNetwork(_rnn_conf(seed=4, updater=Sgd(learning_rate=0.5))
                            ).init()
    pw = ParallelWrapper(par,
                         threshold_algorithm=ThresholdAlgorithm(1e-2),
                         prefetch_buffer=0)
    it = ArrayDataSetIterator(x, y, batch=16)
    pw.fit(it, epochs=1)
    first = pw.score_value
    pw.fit(it, epochs=12)
    assert np.isfinite(pw.score_value)
    assert pw.score_value < first
    assert par.iteration == 13 * 4  # 13 batches x 4 segments
    assert np.all(np.isfinite(par.params_flat()))


def test_tbptt_threshold_back_lt_fwd_converges():
    """Compressed exchange with back < fwd: the no-grad head advance runs
    inside the shard_map scan too."""
    from deeplearning4j_tpu.conf.layers_rnn import LSTM, RnnOutputLayer
    from deeplearning4j_tpu.conf.multilayer import BackpropType

    conf = (NeuralNetConfiguration.builder()
            .seed(6).updater(Sgd(learning_rate=0.5))
            .weight_init(WeightInit.XAVIER).list()
            .layer(LSTM(n_out=10))
            .layer(RnnOutputLayer(n_out=3, activation=Activation.SOFTMAX,
                                  loss_fn=LossMCXENT()))
            .backprop_type(BackpropType.TRUNCATED_BPTT, fwd=5, back=3)
            .set_input_type(InputType.recurrent(4, 20)).build())
    x, y = _rnn_data(16, seed=13)
    par = MultiLayerNetwork(conf).init()
    pw = ParallelWrapper(par, threshold_algorithm=ThresholdAlgorithm(1e-2),
                         prefetch_buffer=0)
    it = ArrayDataSetIterator(x, y, batch=16)
    pw.fit(it, epochs=1)
    first = pw.score_value
    pw.fit(it, epochs=12)
    assert np.isfinite(pw.score_value) and pw.score_value < first


def test_tbptt_threshold_adaptive_tau_retunes_per_segment():
    x, y = _rnn_data(16, seed=14)
    par = MultiLayerNetwork(_rnn_conf(seed=8)).init()
    algo = AdaptiveThresholdAlgorithm(threshold=1e-2)
    pw = ParallelWrapper(par, threshold_algorithm=algo, prefetch_buffer=0)
    pw.fit(ArrayDataSetIterator(x, y, batch=16), epochs=3)
    assert np.isfinite(pw._tau) and pw._tau > 0
    # the per-segment in-scan retune actually moved tau off its initial
    # value (a regression returning the input tau would leave it exact)
    assert pw._tau != algo.threshold
