"""Gradient checks — per-layer matrix in f64 (reference:
``org.deeplearning4j.gradientcheck.*`` test suites, the main correctness
oracle per SURVEY.md §4)."""

import numpy as np
import pytest

from deeplearning4j_tpu.conf import Activation, InputType, WeightInit
from deeplearning4j_tpu.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.conf.layers_cnn import (
    BatchNormalization,
    ConvolutionLayer,
    ConvolutionMode,
    Deconvolution2D,
    GlobalPoolingLayer,
    LocalResponseNormalization,
    PoolingType,
    SeparableConvolution2D,
    SubsamplingLayer,
    Upsampling2D,
)
from deeplearning4j_tpu.conf.losses import (
    LossBinaryXENT,
    LossHinge,
    LossMAE,
    LossMCXENT,
    LossMSE,
)
from deeplearning4j_tpu.conf.multilayer import NeuralNetConfiguration
from deeplearning4j_tpu.conf.regularization import (
    L1Regularization,
    L2Regularization,
)
from deeplearning4j_tpu.conf.updaters import NoOp
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.util.gradcheck import (
    check_layer_input_gradient,
    gradient_check,
)

RNG = np.random.default_rng(42)


def dense_conf(activation, loss, out_act, n_in=4, n_hidden=5, n_out=3,
               regularization=()):
    return (NeuralNetConfiguration.builder()
            .seed(12345)
            .updater(NoOp())
            .weight_init(WeightInit.XAVIER)
            .list()
            .layer(DenseLayer(n_out=n_hidden, activation=activation,
                              regularization=tuple(regularization)))
            .layer(OutputLayer(n_out=n_out, activation=out_act, loss_fn=loss))
            .set_input_type(InputType.feed_forward(n_in))
            .build())


def random_ds(n_in=4, n_out=3, batch=6, onehot=True):
    x = RNG.normal(size=(batch, n_in)).astype(np.float64)
    if onehot:
        y = np.eye(n_out)[RNG.integers(0, n_out, batch)]
    else:
        y = RNG.normal(size=(batch, n_out))
    return DataSet(x, y)


@pytest.mark.parametrize("act", [
    Activation.TANH, Activation.RELU, Activation.SIGMOID, Activation.ELU,
    Activation.SOFTPLUS, Activation.GELU, Activation.SWISH, Activation.CUBE,
    Activation.HARDSIGMOID, Activation.LEAKYRELU,
])
def test_dense_mcxent_gradients(act):
    conf = dense_conf(act, LossMCXENT(), Activation.SOFTMAX)
    res = gradient_check(conf, random_ds())
    assert res.passed, f"{act}: {res.n_failed}/{res.n_checked} failed, " \
                       f"max_rel={res.max_rel_error:.2e}, {res.failures[:3]}"


@pytest.mark.parametrize("loss,out_act,onehot", [
    (LossMSE(), Activation.IDENTITY, False),
    (LossMAE(), Activation.IDENTITY, False),
    (LossMCXENT(), Activation.SOFTMAX, True),
    (LossBinaryXENT(), Activation.SIGMOID, True),
    (LossHinge(), Activation.TANH, False),
])
def test_loss_gradients(loss, out_act, onehot):
    conf = dense_conf(Activation.TANH, loss, out_act)
    res = gradient_check(conf, random_ds(onehot=onehot))
    assert res.passed, f"{loss}: max_rel={res.max_rel_error:.2e} " \
                       f"{res.failures[:3]}"


def test_regularized_gradients():
    conf = dense_conf(Activation.TANH, LossMCXENT(), Activation.SOFTMAX,
                      regularization=[L2Regularization(l2=0.01),
                                      L1Regularization(l1=0.005)])
    # L1/L2 affect updater-side gradient, and score_term adds to the loss:
    # the loss gradient check covers the score_term path
    res = gradient_check(conf, random_ds())
    assert res.passed, res.failures[:3]


def test_cnn_gradients():
    conf = (NeuralNetConfiguration.builder()
            .seed(12345)
            .updater(NoOp())
            .list()
            .layer(ConvolutionLayer(n_out=3, kernel_size=(2, 2),
                                    activation=Activation.TANH,
                                    convolution_mode=ConvolutionMode.SAME))
            .layer(SubsamplingLayer(pooling_type=PoolingType.MAX,
                                    kernel_size=(2, 2), stride=(2, 2)))
            .layer(OutputLayer(n_out=2, activation=Activation.SOFTMAX,
                               loss_fn=LossMCXENT()))
            .set_input_type(InputType.convolutional(4, 4, 2))
            .build())
    x = RNG.normal(size=(3, 4, 4, 2))
    y = np.eye(2)[RNG.integers(0, 2, 3)]
    res = gradient_check(conf, DataSet(x, y))
    assert res.passed, f"max_rel={res.max_rel_error:.2e} {res.failures[:3]}"


def test_batchnorm_gradients():
    conf = (NeuralNetConfiguration.builder()
            .seed(12345)
            .updater(NoOp())
            .list()
            .layer(DenseLayer(n_out=5, activation=Activation.IDENTITY))
            .layer(BatchNormalization())
            .layer(OutputLayer(n_out=3, activation=Activation.SOFTMAX,
                               loss_fn=LossMCXENT()))
            .set_input_type(InputType.feed_forward(4))
            .build())
    res = gradient_check(conf, random_ds())
    assert res.passed, f"max_rel={res.max_rel_error:.2e} {res.failures[:3]}"


@pytest.mark.parametrize("layer,shape", [
    (SubsamplingLayer(pooling_type=PoolingType.AVG, kernel_size=(2, 2),
                      stride=(2, 2)), (2, 4, 4, 3)),
    (SubsamplingLayer(pooling_type=PoolingType.PNORM, kernel_size=(2, 2),
                      stride=(2, 2)), (2, 4, 4, 3)),
    (GlobalPoolingLayer(pooling_type=PoolingType.AVG), (2, 4, 4, 3)),
    (Upsampling2D(size=(2, 2)), (2, 3, 3, 2)),
    (LocalResponseNormalization(), (2, 3, 3, 4)),
    (SeparableConvolution2D(n_out=3, kernel_size=(2, 2),
                            convolution_mode=ConvolutionMode.SAME),
     (2, 4, 4, 2)),
    (Deconvolution2D(n_out=2, kernel_size=(2, 2), stride=(2, 2),
                     convolution_mode=ConvolutionMode.SAME), (2, 3, 3, 2)),
])
def test_layer_input_gradients(layer, shape):
    t = InputType.convolutional(shape[1], shape[2], shape[3])
    x = RNG.normal(size=shape)
    res = check_layer_input_gradient(layer, t, x)
    assert res.passed, f"max_rel={res.max_rel_error:.2e} {res.failures[:3]}"
