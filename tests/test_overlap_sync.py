"""Round-6 hot-path machinery: bucketed overlap-scheduled gradient sync
(parallel/compression.bucketed_psum + ParallelWrapper.gradient_bucket_mb),
the AOT step-executable cache (optimize/aot_cache), and the double-buffered
device ingest ring (datasets/prefetch.DeviceRingIterator).

All under ``JAX_PLATFORMS=cpu`` (conftest): the 8 virtual devices exercise
the real collective/sharding paths; numerics are the oracle.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from deeplearning4j_tpu.conf import Activation, InputType, WeightInit
from deeplearning4j_tpu.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.conf.losses import LossMCXENT
from deeplearning4j_tpu.conf.multilayer import NeuralNetConfiguration
from deeplearning4j_tpu.conf.updaters import Sgd
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
from deeplearning4j_tpu.datasets.prefetch import DeviceRingIterator
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.optimize import aot_cache
from deeplearning4j_tpu.parallel import ParallelWrapper, TrainingMode
from deeplearning4j_tpu.parallel.compression import (
    ThresholdAlgorithm,
    bucket_partition,
    bucketed_psum,
)
from deeplearning4j_tpu.parallel.mesh import DATA_AXIS, shard_map


def _mlp(seed=3):
    """No dropout / no BN: the explicit shard_map exchange folds rng per
    shard and computes BN stats per shard, so the SPMD-vs-shard_map parity
    below is exact only for deterministic per-example nets."""
    conf = (NeuralNetConfiguration.builder()
            .seed(seed).updater(Sgd(learning_rate=0.1))
            .weight_init(WeightInit.XAVIER)
            .list()
            .layer(DenseLayer(n_out=12, activation=Activation.TANH))
            .layer(DenseLayer(n_out=8, activation=Activation.TANH))
            .layer(OutputLayer(n_out=3, activation=Activation.SOFTMAX,
                               loss_fn=LossMCXENT()))
            .set_input_type(InputType.feed_forward(6))
            .build())
    return MultiLayerNetwork(conf).init()


def _batch(rng, n=16):
    x = rng.normal(size=(n, 6)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, n)]
    return DataSet(x, y)


def _params_close(a, b, rtol=2e-5, atol=1e-6, msg=""):
    for k in b:
        for pk in b[k]:
            np.testing.assert_allclose(
                np.asarray(a[k][pk]), np.asarray(b[k][pk]),
                rtol=rtol, atol=atol, err_msg=f"{msg}{k}/{pk}")


# --------------------------------------------------------------------------
# bucket partitioning + bucketed_psum primitive
# --------------------------------------------------------------------------


def test_bucket_partition_reverse_topological_and_complete():
    sizes = [100, 50, 200, 10, 10, 10]
    buckets = bucket_partition(sizes, bucket_bytes=60)
    # every index exactly once
    flat = [i for b in buckets for i in b]
    assert sorted(flat) == list(range(len(sizes)))
    # reverse order: the LAST leaves (first grads out of backprop) lead
    assert flat == list(reversed(range(len(sizes))))
    # size targeting: the three 10s pack together, big leaves go alone
    assert buckets[0] == [5, 4, 3]
    for b in buckets:
        assert b, "no empty buckets"
    # one giant leaf still gets a bucket
    assert bucket_partition([10 ** 9], 1024) == [[0]]


@pytest.mark.parametrize("bucket_bytes", [None, 64, 10 ** 9])
def test_bucketed_psum_matches_fused(bucket_bytes):
    """Inside a shard_map, bucketed and single-fused psum produce
    identical reductions for an uneven pytree."""
    mesh = Mesh(np.array(jax.devices()[:4]), (DATA_AXIS,))
    rng = np.random.default_rng(0)
    tree = {
        "a": jnp.asarray(rng.normal(size=(4, 8, 3)).astype(np.float32)),
        "b": jnp.asarray(rng.normal(size=(4, 2)).astype(np.float32)),
        "c": [jnp.asarray(rng.normal(size=(4, 17)).astype(np.float32)),
              jnp.asarray(rng.normal(size=(4, 1)).astype(np.float32))],
    }

    def body(t):
        return bucketed_psum(t, DATA_AXIS, bucket_bytes)

    def body_ref(t):
        return jax.tree_util.tree_map(
            lambda x: jax.lax.psum(x, DATA_AXIS), t)

    specs = jax.tree_util.tree_map(lambda _: P(DATA_AXIS), tree)
    got = jax.jit(shard_map(body, mesh, in_specs=(specs,),
                            out_specs=specs))(tree)
    want = jax.jit(shard_map(body_ref, mesh, in_specs=(specs,),
                             out_specs=specs))(tree)
    for g, w in zip(jax.tree_util.tree_leaves(got),
                    jax.tree_util.tree_leaves(want)):
        np.testing.assert_array_equal(np.asarray(g), np.asarray(w))


# --------------------------------------------------------------------------
# ParallelWrapper: bucketed sync == unbucketed, all three modes
# --------------------------------------------------------------------------


def test_bucketed_exact_mode_matches_spmd_and_fused():
    """SHARED_GRADIENTS exact: the explicit bucketed shard_map exchange
    (small buckets AND the bucket-size-0 single-fused fallback) matches
    the default XLA-SPMD path elementwise after multiple steps."""
    rng = np.random.default_rng(1)
    ds = _batch(rng)
    out = {}
    for name, kw in (("spmd", {}),
                     ("fused", {"gradient_bucket_mb": 0}),
                     ("bucketed", {"gradient_bucket_mb": 0.0002})):
        net = _mlp()
        ParallelWrapper(net, prefetch_buffer=0, **kw).fit(ds, epochs=2)
        out[name] = net.params
    _params_close(out["fused"], out["spmd"], msg="fused-vs-spmd:")
    # bucketing only regroups the collectives — bit-identical to fused
    _params_close(out["bucketed"], out["fused"], rtol=1e-7, atol=1e-8,
                  msg="bucketed-vs-fused:")


def test_bucketed_threshold_mode_matches_unbucketed():
    """SHARED_GRADIENTS + ThresholdAlgorithm: bucketing the encoded
    message exchange leaves params AND the carried residual identical —
    3 epochs so the residual self-correction crosses steps."""
    rng = np.random.default_rng(2)
    ds = _batch(rng)
    out = {}
    for name, kw in (("plain", {}),
                     ("bucketed", {"gradient_bucket_mb": 0.0002})):
        net = _mlp(seed=5)
        pw = ParallelWrapper(net,
                             threshold_algorithm=ThresholdAlgorithm(1e-3),
                             prefetch_buffer=0, **kw)
        pw.fit(ds, epochs=3)
        out[name] = (net.params,
                     jax.tree_util.tree_map(np.asarray, pw._residual))
    _params_close(out["bucketed"][0], out["plain"][0], rtol=1e-7,
                  atol=1e-8, msg="threshold:")
    for g, w in zip(jax.tree_util.tree_leaves(out["bucketed"][1]),
                    jax.tree_util.tree_leaves(out["plain"][1])):
        np.testing.assert_allclose(g, w, rtol=1e-7, atol=1e-8,
                                   err_msg="residual carry-over")


def test_bucketed_averaging_matches_unbucketed():
    """AVERAGING: the bucketed shard_map barrier-average == the plain
    stacked-mean collective."""
    rng = np.random.default_rng(3)
    ds = _batch(rng)
    out = {}
    for name, kw in (("plain", {}),
                     ("bucketed", {"gradient_bucket_mb": 0.0002})):
        net = _mlp(seed=7)
        ParallelWrapper(net, training_mode=TrainingMode.AVERAGING,
                        averaging_frequency=1, prefetch_buffer=0,
                        **kw).fit(ds, epochs=2)
        out[name] = net.params
    _params_close(out["bucketed"], out["plain"], msg="averaging:")


def test_bucket_config_refusals():
    net = _mlp()
    with pytest.raises(ValueError, match="gradient_bucket_mb"):
        ParallelWrapper(net, gradient_bucket_mb=-1)
    with pytest.raises(ValueError, match="SHARED_GRADIENTS / AVERAGING"):
        ParallelWrapper(net, gradient_bucket_mb=1, expert_parallel=True)


# --------------------------------------------------------------------------
# AOT step-executable cache
# --------------------------------------------------------------------------


def test_aot_cache_hit_on_refit_miss_on_shape_change():
    aot_cache.clear()
    rng = np.random.default_rng(4)
    ds = _batch(rng, n=8)
    net = _mlp(seed=9)
    net.fit_batch(ds)
    s1 = aot_cache.stats()
    assert s1["misses"] >= 1 and s1["compile_seconds"] > 0
    # refit, unchanged shapes: ZERO recompiles (the acceptance invariant)
    net.fit_batch(ds)
    net.fit_batch(ds)
    s2 = aot_cache.stats()
    assert s2["misses"] == s1["misses"], (s1, s2)
    assert s2["hits"] >= s1["hits"] + 2
    # a batch-shape change is a recorded miss, not a silent retrace
    net.fit_batch(_batch(rng, n=4))
    s3 = aot_cache.stats()
    assert s3["misses"] == s2["misses"] + 1


def test_aot_cache_shares_executables_across_instances():
    """A clone (same conf object) must reuse the compiled step — the
    cross-instance point of content-keying the graph signature."""
    aot_cache.clear()
    rng = np.random.default_rng(5)
    ds = _batch(rng, n=8)
    net = _mlp(seed=11)
    net.fit_batch(ds)
    misses = aot_cache.stats()["misses"]
    clone = net.clone()
    clone.fit_batch(ds)
    assert aot_cache.stats()["misses"] == misses
    # and a structurally-identical FRESH conf shares too (content key)
    fresh = _mlp(seed=11)
    fresh.fit_batch(ds)
    assert aot_cache.stats()["misses"] == misses


def test_aot_cache_numerics_unchanged():
    rng = np.random.default_rng(6)
    ds = _batch(rng, n=8)
    import os

    aot_cache.clear()
    a = _mlp(seed=13)
    la = a.fit_batch(ds)
    os.environ["DL4J_TPU_AOT_CACHE"] = "0"
    try:
        b = _mlp(seed=13)
        lb = b.fit_batch(ds)
    finally:
        os.environ.pop("DL4J_TPU_AOT_CACHE", None)
    np.testing.assert_allclose(la, lb, rtol=1e-6)
    _params_close(a.params, b.params, rtol=1e-6, atol=1e-7)


def test_aot_cache_stats_listener_and_system_tab():
    from deeplearning4j_tpu.optimize.listeners import AotCacheStatsListener
    from deeplearning4j_tpu.ui.stats import collect_system_metrics

    aot_cache.clear()
    rng = np.random.default_rng(7)
    ds = _batch(rng, n=8)
    net = _mlp(seed=15)
    lst = AotCacheStatsListener(frequency=1, print_stats=False)
    net.set_listeners(lst)
    net.fit_batch(ds)
    net.fit_batch(ds)
    assert lst.history, "listener collected nothing"
    snap = lst.history[-1]
    assert snap["misses"] >= 1 and "compile_seconds" in snap
    sysm = collect_system_metrics()
    assert "aot_cache" in sysm and sysm["aot_cache"]["misses"] >= 1


def test_samediff_aot_cache_zero_recompiles_across_fits():
    from deeplearning4j_tpu.samediff.core import SameDiff
    from deeplearning4j_tpu.samediff.training import TrainingConfig

    aot_cache.clear()
    rng = np.random.default_rng(8)
    sd = SameDiff.create()
    x = sd.placeholder("x", shape=(None, 4))
    label = sd.placeholder("label", shape=(None, 2))
    w = sd.var("w", shape=(4, 2), key=jax.random.PRNGKey(0))
    out = x @ w
    sd.loss.meanSquaredError(label, out, name="loss")
    sd.set_training_config(
        TrainingConfig.builder()
        .updater(Sgd(learning_rate=0.1))
        .data_set_feature_mapping("x")
        .data_set_label_mapping("label")
        .build())
    feats = rng.normal(size=(8, 4)).astype(np.float32)
    labels = rng.normal(size=(8, 2)).astype(np.float32)
    sd.fit(features=feats, labels=labels)
    misses = aot_cache.stats()["misses"]
    sd.fit(features=feats, labels=labels)
    sd.fit(features=feats, labels=labels)
    s = aot_cache.stats()
    assert s["misses"] == misses, "refit recompiled"
    assert s["hits"] >= 2


def test_samediff_aot_cache_distinguishes_training_configs():
    """Two TrainingConfigs over the SAME graph bake different updaters
    into the step — the cache must key them apart (round-6 review): a
    collision would silently train with the first config's lr."""
    from deeplearning4j_tpu.samediff.core import SameDiff
    from deeplearning4j_tpu.samediff.training import TrainingConfig

    aot_cache.clear()
    rng = np.random.default_rng(9)
    feats = rng.normal(size=(8, 4)).astype(np.float32)
    labels = rng.normal(size=(8, 2)).astype(np.float32)

    def build(lr):
        sd = SameDiff.create()
        x = sd.placeholder("x", shape=(None, 4))
        label = sd.placeholder("label", shape=(None, 2))
        w = sd.var("w", shape=(4, 2), key=jax.random.PRNGKey(3))
        sd.loss.meanSquaredError(label, x @ w, name="loss")
        sd.set_training_config(
            TrainingConfig.builder()
            .updater(Sgd(learning_rate=lr))
            .data_set_feature_mapping("x")
            .data_set_label_mapping("label")
            .build())
        return sd, w

    sd_a, w_a = build(0.1)
    w0 = np.asarray(sd_a.arrays["w"]).copy()
    sd_a.fit(features=feats, labels=labels)
    delta_a = np.abs(np.asarray(sd_a.arrays["w"]) - w0).max()

    sd_b, w_b = build(0.0)  # identical graph, ZERO learning rate
    w0b = np.asarray(sd_b.arrays["w"]).copy()
    sd_b.fit(features=feats, labels=labels)
    delta_b = np.abs(np.asarray(sd_b.arrays["w"]) - w0b).max()

    assert delta_a > 1e-4, "lr=0.1 config did not train"
    assert delta_b == 0.0, (
        "lr=0 config moved params — executable shared across configs")


# --------------------------------------------------------------------------
# double-buffered device ingest
# --------------------------------------------------------------------------


def _ring_batches(n=6):
    return [DataSet(np.full((4, 6), i, np.float32),
                    np.eye(3, dtype=np.float32)[np.full(4, i % 3)])
            for i in range(n)]


def test_device_ring_preserves_order_and_stages_on_device():
    ring = DeviceRingIterator(ListDataSetIterator(_ring_batches()),
                              depth=2, donate=False)
    seen = []
    for b in ring:
        assert isinstance(b.features, jax.Array)
        seen.append(float(np.asarray(b.features)[0, 0]))
    assert seen == [float(i) for i in range(6)]
    assert ring.staged_count == 6


def test_device_ring_donates_consumed_buffers():
    ring = DeviceRingIterator(ListDataSetIterator(_ring_batches()),
                              depth=2, donate=True)
    held = list(ring)
    assert ring.retired_count >= len(held) - 2
    deleted = sum(1 for b in held[:-2] if b.features.is_deleted())
    assert deleted == len(held) - 2, "consumed buffers were not donated"
    # the in-flight tail stays alive for the epoch-end sync
    assert not held[-1].features.is_deleted()


def test_device_ring_never_touches_source_arrays():
    batches = _ring_batches()
    hosts = [b.features for b in batches]
    ring = DeviceRingIterator(ListDataSetIterator(batches), depth=2)
    for _ in ring:
        pass
    for b, h in zip(batches, hosts):
        assert b.features is h, "source DataSet was mutated"


def test_training_through_device_ring_matches_plain():
    batches = _ring_batches()
    plain = _mlp(seed=17)
    ringed = _mlp(seed=17)
    plain.fit(ListDataSetIterator(batches), epochs=2)
    ringed.fit(DeviceRingIterator(ListDataSetIterator(_ring_batches()),
                                  depth=2, donate=True), epochs=2)
    np.testing.assert_allclose(ringed.score_value, plain.score_value,
                               rtol=1e-6)
    _params_close(ringed.params, plain.params, rtol=1e-6, atol=1e-7)
