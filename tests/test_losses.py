import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.conf.activations import Activation
from deeplearning4j_tpu.conf.losses import (
    LossBinaryXENT,
    LossCosineProximity,
    LossFMeasure,
    LossHinge,
    LossKLD,
    LossL1,
    LossL2,
    LossMAE,
    LossMCXENT,
    LossMSE,
    LossMSLE,
    LossPoisson,
    LossSparseMCXENT,
    LossSquaredHinge,
)


def test_mse_matches_numpy(rng):
    labels = rng.normal(size=(4, 3)).astype(np.float32)
    pre = rng.normal(size=(4, 3)).astype(np.float32)
    got = float(LossMSE().score(jnp.asarray(labels), jnp.asarray(pre),
                                Activation.IDENTITY))
    want = np.mean(np.sum((pre - labels) ** 2, axis=1) / 3)
    assert np.isclose(got, want, rtol=1e-5)


def test_l2_is_mse_times_nout(rng):
    labels = rng.normal(size=(4, 5)).astype(np.float32)
    pre = rng.normal(size=(4, 5)).astype(np.float32)
    mse = float(LossMSE().score(jnp.asarray(labels), jnp.asarray(pre), Activation.IDENTITY))
    l2 = float(LossL2().score(jnp.asarray(labels), jnp.asarray(pre), Activation.IDENTITY))
    assert np.isclose(l2, mse * 5, rtol=1e-5)


def test_mcxent_softmax_matches_manual(rng):
    logits = rng.normal(size=(6, 4)).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, size=6)]
    got = float(LossMCXENT().score(jnp.asarray(y), jnp.asarray(logits), Activation.SOFTMAX))
    p = np.exp(logits - logits.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    want = np.mean(-np.sum(y * np.log(p), axis=-1))
    assert np.isclose(got, want, rtol=1e-4)


def test_sparse_mcxent_equals_dense(rng):
    logits = rng.normal(size=(6, 4)).astype(np.float32)
    idx = rng.integers(0, 4, size=6)
    y = np.eye(4, dtype=np.float32)[idx]
    dense = float(LossMCXENT().score(jnp.asarray(y), jnp.asarray(logits), Activation.SOFTMAX))
    sparse = float(
        LossSparseMCXENT().score(jnp.asarray(idx), jnp.asarray(logits), Activation.SOFTMAX)
    )
    assert np.isclose(dense, sparse, rtol=1e-6)


def test_binary_xent_stable_at_extreme_logits():
    pre = jnp.asarray([[40.0], [-40.0]])
    y = jnp.asarray([[1.0], [0.0]])
    val = float(LossBinaryXENT().score(y, pre, Activation.SIGMOID))
    assert np.isfinite(val) and val < 1e-10
    # gradient also finite
    g = jax.grad(lambda z: LossBinaryXENT().score(y, z, Activation.SIGMOID))(pre)
    assert np.all(np.isfinite(np.asarray(g)))


def test_masking_excludes_examples(rng):
    labels = rng.normal(size=(4, 3)).astype(np.float32)
    pre = rng.normal(size=(4, 3)).astype(np.float32)
    mask = np.array([1.0, 1.0, 0.0, 0.0], np.float32)
    got = float(
        LossMSE().score(jnp.asarray(labels), jnp.asarray(pre), Activation.IDENTITY,
                        mask=jnp.asarray(mask))
    )
    want = np.mean(np.sum((pre[:2] - labels[:2]) ** 2, axis=1) / 3)
    assert np.isclose(got, want, rtol=1e-5)


def test_time_series_masking(rng):
    # [batch, time, features] with per-timestep mask
    labels = rng.normal(size=(2, 5, 3)).astype(np.float32)
    pre = rng.normal(size=(2, 5, 3)).astype(np.float32)
    mask = np.zeros((2, 5), np.float32)
    mask[0, :3] = 1.0
    mask[1, :1] = 1.0
    got = float(
        LossMSE().score(jnp.asarray(labels), jnp.asarray(pre), Activation.IDENTITY,
                        mask=jnp.asarray(mask))
    )
    per = np.sum((pre - labels) ** 2, axis=2) / 3
    want = np.sum(per * mask) / mask.sum()
    assert np.isclose(got, want, rtol=1e-5)


def test_weighted_loss(rng):
    labels = rng.normal(size=(4, 2)).astype(np.float32)
    pre = rng.normal(size=(4, 2)).astype(np.float32)
    w = (2.0, 0.5)
    got = float(
        LossMSE(weights=w).score(jnp.asarray(labels), jnp.asarray(pre), Activation.IDENTITY)
    )
    want = np.mean(np.sum((pre - labels) ** 2 * np.asarray(w), axis=1) / 2)
    assert np.isclose(got, want, rtol=1e-5)


@pytest.mark.parametrize(
    "loss,act",
    [
        (LossMAE(), Activation.IDENTITY),
        (LossL1(), Activation.IDENTITY),
        (LossMSLE(), Activation.RELU),
        (LossHinge(), Activation.IDENTITY),
        (LossSquaredHinge(), Activation.IDENTITY),
        (LossCosineProximity(), Activation.IDENTITY),
        (LossPoisson(), Activation.SOFTPLUS),
        (LossKLD(), Activation.SOFTMAX),
        (LossFMeasure(), Activation.SIGMOID),
    ],
)
def test_all_losses_finite_and_differentiable(loss, act, rng):
    pre = jnp.asarray(rng.normal(size=(3, 4)).astype(np.float32))
    labels = jnp.asarray(np.abs(rng.normal(size=(3, 4))).astype(np.float32))
    if act is Activation.SOFTMAX:
        labels = labels / labels.sum(-1, keepdims=True)
    if loss.__class__ in (LossHinge, LossSquaredHinge):
        # symmetric ±1 labels so negative-label handling is exercised
        labels = jnp.asarray(
            np.where(rng.normal(size=(3, 4)) > 0, 1.0, -1.0).astype(np.float32)
        )
    if isinstance(loss, LossFMeasure):
        labels = (labels > 0.5).astype(jnp.float32)
    val = loss.score(labels, pre, act)
    assert np.isfinite(float(val))
    g = jax.grad(lambda z: loss.score(labels, z, act))(pre)
    assert np.all(np.isfinite(np.asarray(g)))
