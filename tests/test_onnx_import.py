"""ONNX import (reference ``OnnxGraphMapper`` — partial mapper) with
handcrafted models + numpy oracles."""

import numpy as np
import pytest

from deeplearning4j_tpu.imports.onnx import (
    OnnxGraphMapper,
    UnsupportedOnnxOpException,
)
from deeplearning4j_tpu.imports.protos import onnx_model_pb2 as ox


def _model():
    m = ox.ModelProto()
    m.ir_version = 8
    op = m.opset_import.add()
    op.version = 13
    return m


def _input(g, name, shape):
    vi = g.input.add()
    vi.name = name
    tt = vi.type.tensor_type
    tt.elem_type = 1
    for d in shape:
        dim = tt.shape.dim.add()
        if d:
            dim.dim_value = d
        else:
            dim.dim_param = "N"


def _init(g, name, arr):
    arr = np.asarray(arr)
    t = g.initializer.add()
    t.name = name
    t.data_type = {np.dtype(np.float32): 1,
                   np.dtype(np.int64): 7}[arr.dtype]
    t.dims.extend(arr.shape)
    t.raw_data = arr.tobytes()


def _node(g, op_type, inputs, outputs, **attrs):
    n = g.node.add()
    n.op_type = op_type
    n.input.extend(inputs)
    n.output.extend(outputs)
    for k, v in attrs.items():
        a = n.attribute.add()
        a.name = k
        if isinstance(v, float):
            a.type = 1
            a.f = v
        elif isinstance(v, int):
            a.type = 2
            a.i = v
        elif isinstance(v, str):
            a.type = 3
            a.s = v.encode()
        elif isinstance(v, (list, tuple)):
            a.type = 7
            a.ints.extend(v)
    return n


def test_import_gemm_mlp(rng):
    w1 = rng.normal(size=(4, 8)).astype(np.float32)
    b1 = rng.normal(size=(8,)).astype(np.float32)
    w2 = rng.normal(size=(8, 3)).astype(np.float32)
    m = _model()
    g = m.graph
    _input(g, "x", (0, 4))
    _init(g, "w1", w1)
    _init(g, "b1", b1)
    _init(g, "w2", w2)
    _node(g, "Gemm", ["x", "w1", "b1"], ["h"], alpha=1.0, beta=1.0)
    _node(g, "Relu", ["h"], ["hr"])
    _node(g, "MatMul", ["hr", "w2"], ["logits"])
    _node(g, "Softmax", ["logits"], ["probs"], axis=-1)

    sd = OnnxGraphMapper.import_graph(m.SerializeToString())
    x = rng.normal(size=(5, 4)).astype(np.float32)
    out = np.asarray(sd.output({"x": x}, "probs")["probs"])
    h = np.maximum(x @ w1 + b1, 0)
    logits = h @ w2
    want = np.exp(logits) / np.exp(logits).sum(-1, keepdims=True)
    np.testing.assert_allclose(out, want, rtol=1e-5, atol=1e-6)


def test_import_nchw_conv(rng):
    k = rng.normal(size=(4, 2, 3, 3), scale=0.3).astype(np.float32)  # OIHW
    kb = rng.normal(size=(4,)).astype(np.float32)
    m = _model()
    g = m.graph
    _input(g, "img", (0, 2, 8, 8))  # NCHW
    _init(g, "k", k)
    _init(g, "kb", kb)
    _node(g, "Conv", ["img", "k", "kb"], ["conv"],
          kernel_shape=[3, 3], strides=[1, 1], pads=[1, 1, 1, 1])
    _node(g, "Relu", ["conv"], ["r"])
    _node(g, "MaxPool", ["r"], ["p"], kernel_shape=[2, 2], strides=[2, 2])
    _node(g, "GlobalAveragePool", ["p"], ["gap"])
    _node(g, "Flatten", ["gap"], ["flat"], axis=1)

    sd = OnnxGraphMapper.import_graph(m.SerializeToString())
    x = rng.normal(size=(2, 2, 8, 8)).astype(np.float32)
    out = np.asarray(sd.output({"img": x}, "flat")["flat"])
    assert out.shape == (2, 4)
    # oracle
    import jax

    ref = jax.lax.conv_general_dilated(
        x, k, (1, 1), [(1, 1), (1, 1)],
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    ref = np.maximum(np.asarray(ref) + kb[None, :, None, None], 0)
    pooled = ref.reshape(2, 4, 4, 2, 4, 2).max(axis=(3, 5))
    np.testing.assert_allclose(out, pooled.mean(axis=(2, 3)),
                               rtol=1e-4, atol=1e-5)


def test_import_batchnorm_reshape(rng):
    m = _model()
    g = m.graph
    _input(g, "x", (0, 3, 4, 4))
    _init(g, "gamma", np.asarray([1.0, 2.0, 0.5], np.float32))
    _init(g, "beta", np.asarray([0.1, -0.1, 0.0], np.float32))
    _init(g, "mean", np.asarray([0.5, -0.5, 0.0], np.float32))
    _init(g, "var", np.asarray([1.0, 4.0, 0.25], np.float32))
    _node(g, "BatchNormalization", ["x", "gamma", "beta", "mean", "var"],
          ["bn"], epsilon=1e-3)
    _init(g, "shape", np.asarray([-1, 48], np.int64))
    _node(g, "Reshape", ["bn", "shape"], ["flat"])
    sd = OnnxGraphMapper.import_graph(m.SerializeToString())
    x = rng.normal(size=(2, 3, 4, 4)).astype(np.float32)
    out = np.asarray(sd.output({"x": x}, "flat")["flat"])
    assert out.shape == (2, 48)
    want = ((x - np.asarray([0.5, -0.5, 0.0])[None, :, None, None])
            / np.sqrt(np.asarray([1.0, 4.0, 0.25])[None, :, None, None]
                      + 1e-3)
            * np.asarray([1.0, 2.0, 0.5])[None, :, None, None]
            + np.asarray([0.1, -0.1, 0.0])[None, :, None, None])
    np.testing.assert_allclose(out, want.reshape(2, 48), rtol=1e-4,
                               atol=1e-5)


def test_unsupported_onnx_op_raises(rng):
    m = _model()
    g = m.graph
    _input(g, "x", (0, 4))
    _node(g, "LSTM", ["x"], ["y"])
    with pytest.raises(UnsupportedOnnxOpException) as e:
        OnnxGraphMapper.import_graph(m.SerializeToString())
    assert "LSTM" in str(e.value)


def test_reshape_zero_dim_and_identity_output(rng):
    m = _model()
    g = m.graph
    _input(g, "x", (0, 3, 4))
    _init(g, "shape", np.asarray([0, 12], np.int64))
    _node(g, "Reshape", ["x", "shape"], ["r"])
    _node(g, "Identity", ["r"], ["final_output"])
    vo = g.output.add()
    vo.name = "final_output"
    sd = OnnxGraphMapper.import_graph(m.SerializeToString())
    x = rng.normal(size=(2, 3, 4)).astype(np.float32)
    out = np.asarray(sd.output({"x": x}, "final_output")["final_output"])
    np.testing.assert_allclose(out, x.reshape(2, 12), rtol=1e-6)


def test_unsqueeze_negative_axes(rng):
    m = _model()
    g = m.graph
    _input(g, "x", (0, 3))
    _node(g, "Unsqueeze", ["x"], ["u"], axes=[-2, -1])
    sd = OnnxGraphMapper.import_graph(m.SerializeToString())
    x = rng.normal(size=(2, 3)).astype(np.float32)
    out = np.asarray(sd.output({"x": x}, "u")["u"])
    assert out.shape == (2, 3, 1, 1)


def test_clip_empty_optional_input(rng):
    m = _model()
    g = m.graph
    _input(g, "x", (0, 3))
    _init(g, "maxv", np.asarray(0.5, np.float32).reshape(()))
    n = _node(g, "Clip", [], ["c"])
    n.input.extend(["x", "", "maxv"])  # min omitted via empty name
    sd = OnnxGraphMapper.import_graph(m.SerializeToString())
    x = np.asarray([[-2.0, 0.2, 3.0]], np.float32)
    out = np.asarray(sd.output({"x": x}, "c")["c"])
    np.testing.assert_allclose(out, [[-2.0, 0.2, 0.5]], rtol=1e-6)


def test_same_lower_rejected(rng):
    m = _model()
    g = m.graph
    _input(g, "x", (0, 2, 8, 8))
    _init(g, "k", rng.normal(size=(4, 2, 2, 2)).astype(np.float32))
    _node(g, "Conv", ["x", "k"], ["c"], kernel_shape=[2, 2],
          auto_pad="SAME_LOWER")
    with pytest.raises(UnsupportedOnnxOpException):
        OnnxGraphMapper.import_graph(m.SerializeToString())


def test_pad_constant_value(rng):
    m = _model()
    g = m.graph
    _input(g, "x", (0, 2))
    _node(g, "Pad", ["x"], ["p"], pads=[0, 1, 0, 1], value=5.0)
    sd = OnnxGraphMapper.import_graph(m.SerializeToString())
    x = np.ones((1, 2), np.float32)
    out = np.asarray(sd.output({"x": x}, "p")["p"])
    np.testing.assert_allclose(out, [[5.0, 1.0, 1.0, 5.0]])


def test_external_data_rejected():
    m = _model()
    g = m.graph
    _input(g, "x", (0, 2))
    t = g.initializer.add()
    t.name = "w"
    t.data_type = 1
    t.dims.extend([2, 2])  # no inline payload at all
    _node(g, "MatMul", ["x", "w"], ["y"])
    with pytest.raises(UnsupportedOnnxOpException) as e:
        OnnxGraphMapper.import_graph(m.SerializeToString())
    assert "EXTERNAL" in str(e.value)


def test_fp16_int32_bitpattern_decodes():
    m = _model()
    g = m.graph
    _input(g, "x", (0, 2))
    t = g.initializer.add()
    t.name = "w"
    t.data_type = 10  # FLOAT16
    t.dims.extend([2])
    t.int32_data.extend(
        np.asarray([1.0, -2.5], np.float16).view(np.uint16).tolist())
    _node(g, "Add", ["x", "w"], ["y"])
    sd = OnnxGraphMapper.import_graph(m.SerializeToString())
    np.testing.assert_allclose(
        np.asarray(sd.arrays["w"], np.float32), [1.0, -2.5])


def test_legacy_softmax_flattens(rng):
    m = _model()
    m.opset_import[0].version = 11
    g = m.graph
    _input(g, "x", (0, 2, 3))
    _node(g, "Softmax", ["x"], ["p"])  # no axis attr -> legacy axis=1
    sd = OnnxGraphMapper.import_graph(m.SerializeToString())
    x = rng.normal(size=(2, 2, 3)).astype(np.float32)
    out = np.asarray(sd.output({"x": x}, "p")["p"])
    flat = x.reshape(2, 6)
    want = (np.exp(flat) / np.exp(flat).sum(-1, keepdims=True)).reshape(
        2, 2, 3)
    np.testing.assert_allclose(out, want, rtol=1e-5)


def test_identity_propagates_static(rng):
    m = _model()
    g = m.graph
    _input(g, "x", (0, 6))
    _init(g, "shape", np.asarray([0, 2, 3], np.int64))
    _node(g, "Identity", ["shape"], ["shape_id"])
    _node(g, "Reshape", ["x", "shape_id"], ["r"])
    sd = OnnxGraphMapper.import_graph(m.SerializeToString())
    x = rng.normal(size=(2, 6)).astype(np.float32)
    assert np.asarray(sd.output({"x": x}, "r")["r"]).shape == (2, 2, 3)


def test_bf16_int32_bitpattern_decodes():
    import ml_dtypes

    m = _model()
    g = m.graph
    _input(g, "x", (0, 2))
    t = g.initializer.add()
    t.name = "w"
    t.data_type = 16  # BFLOAT16 via int32_data bit patterns
    t.dims.extend([2])
    t.int32_data.extend(
        np.asarray([1.5, -3.0], ml_dtypes.bfloat16).view(
            np.uint16).astype(np.int32).tolist())
    _node(g, "Add", ["x", "w"], ["y"])
    sd = OnnxGraphMapper.import_graph(m.SerializeToString())
    np.testing.assert_allclose(np.asarray(sd.arrays["w"], np.float32),
                               [1.5, -3.0])
