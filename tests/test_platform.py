"""Multi-tenant serving platform (parallel.platform): versioned
registry with digest-refused corruption, atomic hot-swap behind the
``model.swap`` fault site, seeded canary routing with deterministic
automatic rollback, per-tenant fault isolation (quotas, warmup budgets,
breakers), and the named HTTP 404/503 surfaces.

Chaos invariants pinned here (ISSUE 13 acceptance):
- same seed + same fault plan → same rollback request index;
- the healthy co-tenant's responses stay BYTE-identical with zero
  recompiles while the faulted tenant trips, sheds, and rolls back;
- a kill/fault mid-swap or mid-publish leaves the registry
  digest-verified on the prior version.

All AOT assertions read counter DELTAS (the cache is process-global);
nets that must compile cold use hidden widths no other test uses.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.conf import Activation, InputType, WeightInit
from deeplearning4j_tpu.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.conf.losses import LossMCXENT
from deeplearning4j_tpu.conf.multilayer import NeuralNetConfiguration
from deeplearning4j_tpu.conf.updaters import Sgd
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.optimize import aot_cache
from deeplearning4j_tpu.parallel.batcher import (
    BatchingConfig,
    ServerOverloadedError,
)
from deeplearning4j_tpu.parallel.platform import (
    CanaryGate,
    HostOverloadedError,
    ModelIntegrityError,
    ModelPlatform,
    ModelRegistry,
    TenantConfig,
    UnknownModelError,
)
from deeplearning4j_tpu.parallel.serving import InferenceServer
from deeplearning4j_tpu import resilience
from deeplearning4j_tpu.resilience import FaultPlan
from deeplearning4j_tpu.resilience.faults import InjectedFault
from deeplearning4j_tpu.telemetry import REGISTRY

pytestmark = pytest.mark.platform


def _mlp(seed=0, hidden=8, n_in=4, n_out=3):
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Sgd(0.1))
            .weight_init(WeightInit.XAVIER).list()
            .layer(DenseLayer(n_out=hidden, activation=Activation.TANH))
            .layer(OutputLayer(n_out=n_out, activation=Activation.SOFTMAX,
                               loss_fn=LossMCXENT()))
            .set_input_type(InputType.feed_forward(n_in)).build())
    return MultiLayerNetwork(conf).init()


def _x(rows=2, n_in=4, seed=0):
    return np.random.default_rng(seed).normal(
        size=(rows, n_in)).astype(np.float32)


def _bump(net, delta=0.1):
    """A "newly trained" version of ``net``: SAME configuration (same
    conf-derived AOT graph key — the real version-rollout shape, where
    weights changed but the architecture didn't), different weights."""
    net2 = MultiLayerNetwork(net.conf).init()
    net2.set_params_flat(np.asarray(net.params_flat()) + delta)
    return net2


def _cfg(**over):
    over.setdefault("max_batch", 4)
    return TenantConfig(batching=BatchingConfig(**over))


# --- registry ---------------------------------------------------------------

def test_registry_publish_load_roundtrip(tmp_path):
    reg = ModelRegistry(tmp_path)
    v1 = reg.publish("m", _mlp(seed=1))
    v2 = reg.publish("m", _mlp(seed=2))
    assert (v1, v2) == (1, 2)
    assert reg.models() == ["m"]
    assert reg.versions("m") == [1, 2]
    assert reg.latest_version("m") == 2
    assert reg.verify("m", 1) and reg.verify("m", 2)
    x = _x()
    net1, ver1 = reg.load("m", 1)
    latest, ver = reg.load("m")
    assert (ver1, ver) == (1, 2)
    # distinct seeds -> distinct weights -> distinct outputs
    assert not np.array_equal(np.asarray(net1.output(x)),
                              np.asarray(latest.output(x)))
    # round-trip exactness: the restored latest matches the source
    assert np.array_equal(np.asarray(latest.output(x)),
                          np.asarray(reg.load("m", 2)[0].output(x)))


def test_registry_unknown_model_and_version(tmp_path):
    reg = ModelRegistry(tmp_path)
    with pytest.raises(UnknownModelError, match="unknown model 'ghost'"):
        reg.load("ghost")
    reg.publish("m", _mlp())
    with pytest.raises(UnknownModelError, match="no version 9"):
        reg.load("m", 9)
    with pytest.raises(ValueError, match="invalid model name"):
        reg.publish("../escape", _mlp())


def test_registry_digest_mismatch_refused(tmp_path):
    reg = ModelRegistry(tmp_path)
    reg.publish("m", _mlp(seed=1))
    reg.publish("m", _mlp(seed=2))
    with open(tmp_path / "m" / "v0002.zip", "ab") as f:
        f.write(b"bitrot")
    assert reg.verify("m", 1) and not reg.verify("m", 2)
    with pytest.raises(ModelIntegrityError, match="sha256 mismatch"):
        reg.load("m", 2)
    # the prior version is untouched and loads digest-verified
    assert reg.load("m", 1)[1] == 1


def test_registry_load_fault_retried(tmp_path):
    reg = ModelRegistry(tmp_path)
    reg.publish("m", _mlp())
    snap = REGISTRY.snapshot(run_collectors=False)
    r0 = snap.get('dl4j_retries_total{op="model.load"}', 0)
    # one transient failure: MODEL_LOAD_RETRY's second attempt lands
    with FaultPlan(seed=1).inject("model.load", on_calls=[1]).armed():
        net, ver = reg.load("m")
    assert ver == 1 and net is not None
    snap = REGISTRY.snapshot(run_collectors=False)
    assert snap.get('dl4j_retries_total{op="model.load"}', 0) == r0 + 1
    # persistent failure exhausts the 2-attempt budget and surfaces
    with FaultPlan(seed=1).inject("model.load", on_calls=[1, 2]).armed():
        with pytest.raises(InjectedFault):
            reg.load("m")


def test_kill_mid_publish_leaves_prior_verified(tmp_path):
    reg = ModelRegistry(tmp_path)
    reg.publish("m", _mlp(seed=1))
    # the zip assembly dies mid-write (write_model's permanent
    # checkpoint.write site): no v2 zip is published, the manifest never
    # learns of v2, and v1 stays digest-verified
    with FaultPlan(seed=2).inject("checkpoint.write", on_calls=[1]).armed():
        with pytest.raises(InjectedFault):
            reg.publish("m", _mlp(seed=2))
    assert reg.versions("m") == [1]
    assert reg.verify("m")
    assert not list((tmp_path / "m").glob("*.tmp.*"))
    assert reg.load("m")[1] == 1


# --- deploy / swap ----------------------------------------------------------

def test_deploy_predict_and_stats(tmp_path):
    reg = ModelRegistry(tmp_path)
    reg.publish("m", _mlp(seed=1))
    with ModelPlatform(reg) as plat:
        out = plat.deploy("m", config=_cfg())
        assert out["version"] == 1 and not out["warmup_truncated"]
        x = _x()
        y = np.asarray(plat.predict("m", x))
        assert y.shape == (2, 3)
        st = plat.stats()["m"]
        assert st["version"] == 1
        assert st["breaker"] == "closed"
        assert st["warmup_budget"]["compiles"] >= 0
        with pytest.raises(UnknownModelError, match="unknown model"):
            plat.predict("ghost", x)


def test_swap_atomic_and_fault_mid_swap(tmp_path):
    reg = ModelRegistry(tmp_path)
    v1 = _mlp(seed=1, hidden=27)
    reg.publish("m", v1)
    reg.publish("m", _bump(v1))
    x = _x()
    with ModelPlatform(reg) as plat:
        plat.deploy("m", version=1, config=_cfg())
        y1 = np.asarray(plat.predict("m", x)).tobytes()
        # a fault between load and publish = partial swap: the incumbent
        # keeps serving, the tenant record never moves
        with FaultPlan(seed=3).inject("model.swap", on_calls=[1]).armed():
            with pytest.raises(InjectedFault):
                plat.swap("m", 2)
        assert plat.stats()["m"]["version"] == 1
        assert np.asarray(plat.predict("m", x)).tobytes() == y1
        # clean swap: same conf -> warmed buckets stay valid, zero
        # recompiles; outputs flip to v2's weights
        miss0 = aot_cache.stats()["misses"]
        assert plat.swap("m", 2)["version"] == 2
        y2 = np.asarray(plat.predict("m", x)).tobytes()
        assert y2 != y1
        assert aot_cache.stats()["misses"] == miss0
        assert plat.stats()["m"]["version"] == 2


def test_swap_to_corrupt_version_refused(tmp_path):
    reg = ModelRegistry(tmp_path)
    reg.publish("m", _mlp(seed=1))
    reg.publish("m", _mlp(seed=2))
    with open(tmp_path / "m" / "v0002.zip", "r+b") as f:
        f.seek(10)
        f.write(b"\xff\xff\xff")
    x = _x()
    with ModelPlatform(reg) as plat:
        plat.deploy("m", version=1, config=_cfg())
        y1 = np.asarray(plat.predict("m", x)).tobytes()
        with pytest.raises(ModelIntegrityError):
            plat.swap("m", 2)
        assert plat.stats()["m"]["version"] == 1
        assert np.asarray(plat.predict("m", x)).tobytes() == y1


def test_wedged_swap_keeps_incumbent_serving(tmp_path):
    reg = ModelRegistry(tmp_path)
    reg.publish("m", _mlp(seed=1))
    reg.publish("m", _mlp(seed=2))
    x = _x()
    with ModelPlatform(reg) as plat:
        plat.deploy("m", version=1, config=_cfg())
        y1 = np.asarray(plat.predict("m", x)).tobytes()
        done = threading.Event()

        def slow_swap():
            # delay at the model.swap site = a wedged swap in flight
            with FaultPlan(seed=4).inject(
                    "model.swap", action="delay", delay_s=0.4).armed():
                plat.swap("m", 2)
            done.set()

        t = threading.Thread(target=slow_swap, daemon=True)
        t.start()
        served = 0
        while not done.is_set() and served < 50:
            # traffic flows on the incumbent for the whole wedge window
            assert np.asarray(plat.predict("m", x)).tobytes() == y1
            served += 1
        t.join(timeout=5)
        assert done.is_set() and served > 0
        assert plat.stats()["m"]["version"] == 2


# --- isolation: quotas, host cap, warmup budgets ----------------------------

def test_quota_flood_isolated_to_one_tenant(tmp_path):
    reg = ModelRegistry(tmp_path)
    reg.publish("a", _mlp(seed=1, hidden=8))
    reg.publish("b", _mlp(seed=2, hidden=10))
    x = _x()
    with ModelPlatform(reg, seed=5) as plat:
        plat.deploy("a", config=_cfg())
        plat.deploy("b", config=_cfg(max_queue=2))
        ya = np.asarray(plat.predict("a", x)).tobytes()
        miss0 = aot_cache.stats()["misses"]
        # park b's dispatcher (the serving-suite inert idiom) and flood
        # past its private queue quota — deterministic, no timing races
        eng_b = plat.engine("b")
        eng_b._ensure_thread = lambda: None
        held = [eng_b.submit([x]) for _ in range(2)]
        with pytest.raises(ServerOverloadedError, match="model 'b'"):
            eng_b.submit([x])
        # the flood degrades ONLY b: a serves promptly, bytes pinned
        for _ in range(3):
            assert np.asarray(plat.predict("a", x)).tobytes() == ya
        del eng_b.__dict__["_ensure_thread"]  # un-park the dispatcher
        eng_b._ensure_thread()
        for h in held:
            eng_b.result(h)
        assert aot_cache.stats()["misses"] == miss0


def test_host_overload_distinct_from_model_shed(tmp_path):
    reg = ModelRegistry(tmp_path)
    reg.publish("a", _mlp(seed=1, hidden=8))
    reg.publish("b", _mlp(seed=2, hidden=10))
    x = _x()
    with ModelPlatform(reg, host_max_pending=2) as plat:
        plat.deploy("a", config=_cfg())
        plat.deploy("b", config=_cfg(max_queue=64))
        # park b's dispatcher (the serving-suite inert idiom) so its
        # flood stays GENUINELY queued — deterministic, no timing
        eng_b = plat.engine("b")
        eng_b._ensure_thread = lambda: None
        held = [eng_b.submit([x]) for _ in range(2)]
        # the host-wide cap is now exhausted by b alone: even the
        # HEALTHY tenant sheds, and the error names the HOST — a client
        # can tell this apart from "model 'b' serving queue full"
        with pytest.raises(HostOverloadedError, match="host overloaded"):
            plat.predict("a", x)
        # host overload is a ServerOverloadedError (HTTP 503) subclass,
        # distinguishable by class and message from a model's own shed
        assert issubclass(HostOverloadedError, ServerOverloadedError)
        del eng_b.__dict__["_ensure_thread"]  # un-park the dispatcher
        eng_b._ensure_thread()
        for h in held:
            eng_b.result(h)
        assert np.asarray(plat.predict("a", x)).shape == (2, 3)


def test_warmup_budget_truncates_only_that_tenant(tmp_path):
    from deeplearning4j_tpu.analysis.findings import LOG

    reg = ModelRegistry(tmp_path)
    # unique widths: these tenants must compile cold for the budget to
    # have anything to refuse
    reg.publish("cheap", _mlp(seed=1, hidden=29))
    reg.publish("storm", _mlp(seed=2, hidden=31))
    with ModelPlatform(reg) as plat:
        out = plat.deploy("cheap", config=_cfg(max_batch=4))
        assert not out["warmup_truncated"]
        cfg = _cfg(max_batch=8)
        cfg.warmup_max_compiles = 2
        storm = plat.deploy("storm", config=cfg)
        assert storm["warmup_truncated"]
        assert storm["warmup"]["compiles"] == 2  # charged, then refused
        # the truncation is on /analysis as a PLT301 finding
        assert any(f.rule == "PLT301" and "storm" in f.location
                   for f in LOG.items())
        # and the tenant still SERVES (uncompiled buckets just compile
        # lazily on first traffic — degraded warmup, not a dead tenant)
        assert np.asarray(plat.predict("storm", _x())).shape == (2, 3)
        # the co-tenant's warmup was complete and its traffic compiles
        # nothing new
        miss0 = aot_cache.stats()["misses"]
        plat.predict("cheap", _x())
        assert aot_cache.stats()["misses"] == miss0


# --- canary -----------------------------------------------------------------

def _canary_chaos_run(reg, x, seed):
    """One full canary-chaos pass; returns (rollback record, healthy
    tenant bytes pinned, recompiles, shed count, tripped)."""
    plat = ModelPlatform(reg, seed=seed)
    plat.deploy("good", version=1, config=_cfg())
    plat.deploy("bad", version=1, config=_cfg())
    y_good = np.asarray(plat.predict("good", x)).tobytes()
    y_bad_v1 = np.asarray(plat.predict("bad", x)).tobytes()
    plat.deploy_canary("bad", 2, fraction=0.5,
                       gate=CanaryGate(max_consecutive_failures=3))
    miss0 = aot_cache.stats()["misses"]
    plan = FaultPlan(seed=11).inject("serving.launch:bad#canary")
    pinned, sheds, tripped = True, 0, False
    with plan.armed():
        for _ in range(30):
            try:
                plat.predict("bad", x)
            except Exception:
                sheds += 1
            st = plat.stats()["bad"]
            tripped = tripped or st.get("canary", {}).get(
                "breaker") == "open"
            pinned = pinned and (np.asarray(
                plat.predict("good", x)).tobytes() == y_good)
    st = plat.stats()["bad"]
    rollback = st.get("last_rollback")
    # rollback restored the incumbent: v1 serves again, bit-identical
    post = np.asarray(plat.predict("bad", x)).tobytes()
    recompiles = aot_cache.stats()["misses"] - miss0
    plat.close()
    return rollback, pinned and post == y_bad_v1, recompiles, sheds


def test_canary_rollback_chaos_deterministic(tmp_path):
    """ISSUE 13 acceptance: a seeded fault plan degrades the canary
    mid-traffic, the gate trips, rollback restores the incumbent — and
    the whole run replays bit-identically: same seed → same rollback
    request index, healthy co-tenant byte-identical with ZERO recompiles
    throughout."""
    reg = ModelRegistry(tmp_path)
    reg.publish("good", _mlp(seed=1, hidden=8))
    bad_v1 = _mlp(seed=2, hidden=12)
    reg.publish("bad", bad_v1)
    reg.publish("bad", _bump(bad_v1))
    x = _x()
    r1 = _canary_chaos_run(reg, x, seed=9)
    r2 = _canary_chaos_run(reg, x, seed=9)
    for rollback, restored, recompiles, sheds in (r1, r2):
        assert rollback is not None, "gate never tripped"
        assert rollback["version"] == 2
        assert "consecutive canary failures" in rollback["reason"]
        assert restored, "co-tenant or post-rollback bytes diverged"
        assert recompiles == 0
        assert sheds >= 3  # the canary's failures surfaced to callers
    # the deterministic heart: both runs rolled back at the SAME request
    assert r1[0]["at_request"] == r2[0]["at_request"]
    assert r1[0]["canary"]["requests"] == r2[0]["canary"]["requests"]
    # the retired canary's state gauge was zeroed at rollback — the
    # model must not keep reporting "open" after it stopped shedding
    snap = REGISTRY.snapshot(run_collectors=False)
    assert snap['dl4j_circuit_state{breaker="serving:bad#canary"}'] == 0


def test_canary_promote_zero_recompiles(tmp_path):
    reg = ModelRegistry(tmp_path)
    v1 = _mlp(seed=1, hidden=14)
    reg.publish("m", v1)
    reg.publish("m", _bump(v1))
    x = _x()
    with ModelPlatform(reg, seed=2) as plat:
        plat.deploy("m", version=1, config=_cfg())
        y1 = np.asarray(plat.predict("m", x)).tobytes()
        plat.deploy_canary("m", 2, fraction=0.5)
        miss0 = aot_cache.stats()["misses"]
        for _ in range(10):
            plat.predict("m", x)
        st = plat.stats()["m"]["canary"]
        assert st["requests"] > 0 and st["failures"] == 0
        out = plat.promote("m")
        assert out["version"] == 2
        y2 = np.asarray(plat.predict("m", x)).tobytes()
        assert y2 != y1  # v2's weights serve now
        assert "canary" not in plat.stats()["m"]
        assert aot_cache.stats()["misses"] == miss0
        with pytest.raises(RuntimeError, match="no canary"):
            plat.promote("m")


def test_canary_fraction_routing_is_seeded(tmp_path):
    reg = ModelRegistry(tmp_path)
    v1 = _mlp(seed=1, hidden=16)
    reg.publish("m", v1)
    reg.publish("m", _bump(v1))
    x = _x()

    def arm_counts(seed):
        plat = ModelPlatform(reg, seed=seed)
        plat.deploy("m", version=1, config=_cfg())
        plat.deploy_canary("m", 2, fraction=0.3,
                           gate=CanaryGate(min_requests=10 ** 6))
        for _ in range(40):
            plat.predict("m", x)
        st = plat.stats()["m"]
        counts = (st["canary"]["requests"], st["requests"])
        plat.close()
        return counts

    a, b, c = arm_counts(1), arm_counts(1), arm_counts(2)
    assert a == b  # same seed: identical request routing
    assert a[0] > 0 and a[1] > 0  # both arms actually took traffic
    assert a != c  # a different platform seed routes differently


# --- breaker aggregation (/health) ------------------------------------------

def test_health_aggregates_breakers_per_model_name():
    from deeplearning4j_tpu.resilience.breaker import CircuitBreaker

    primary = CircuitBreaker(name="serving:agg-test",
                             failure_threshold=1)
    canary = CircuitBreaker(name="serving:agg-test#canary",
                            failure_threshold=1)
    primary.on_success()
    canary.on_failure()  # trips open
    # the arms keep distinct metric series, but /health groups them by
    # the pre-# prefix: ONE entry per model, reporting the WORST of its
    # live breakers plus how many it aggregated — one shedding arm is
    # visible even while the other is healthy
    st = resilience.status()["circuit_breakers"]["serving:agg-test"]
    assert st["state"] == "open"
    assert st["breakers"] == 2
    assert sorted(st["states"]) == ["closed", "open"]
    assert st["tripped_total"] == 1


# --- HTTP surfaces ----------------------------------------------------------

def _post(base, path, payload):
    req = urllib.request.Request(base + path, json.dumps(payload).encode(),
                                 {"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=15) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_http_named_404_and_503(tmp_path):
    reg = ModelRegistry(tmp_path)
    reg.publish("alpha", _mlp(seed=1, hidden=8))
    reg.publish("beta", _mlp(seed=2, hidden=10))
    x = [[0.1, 0.2, 0.3, 0.4]]
    with ModelPlatform(reg, seed=1) as plat:
        plat.deploy("alpha", config=_cfg())
        plat.deploy("beta", config=_cfg())
        srv = InferenceServer(plat).start(port=0)
        try:
            base = f"http://127.0.0.1:{srv.port}"
            code, body = _post(base, "/predict/alpha", {"inputs": [x]})
            assert code == 200 and len(body["outputs"][0]) == 1
            code, body = _post(base, "/models/beta/predict",
                               {"inputs": [x]})
            assert code == 200
            # unknown model: NAMED 404 (not a KeyError 500), and it
            # tells the client what IS deployed
            code, body = _post(base, "/predict/ghost", {"inputs": [x]})
            assert code == 404
            assert "ghost" in body["error"]
            assert body["models"] == ["alpha", "beta"]
            # bare /predict on a multi-model host: same named surface
            code, body = _post(base, "/predict", {"inputs": [x]})
            assert code == 404 and body["models"] == ["alpha", "beta"]
            # malformed input is a 400 for the sender only
            code, body = _post(base, "/predict/alpha",
                               {"inputs": [[[0.1, 0.2]]]})
            assert code == 400
            # ragged nesting too (numpy RAISES on inhomogeneous lists —
            # must surface as the sender's 400, never a host 500)
            code, body = _post(base, "/predict/alpha",
                               {"inputs": [[[0.1, 0.2], [0.3]]]})
            assert code == 400 and "malformed" in body["error"]
            # trip beta's breaker: the 503 names the model, its scope,
            # and the breaker state — distinguishable from host overload
            with FaultPlan(seed=8).inject("serving.launch:beta").armed():
                for _ in range(6):
                    _post(base, "/predict/beta", {"inputs": [x]})
                code, body = _post(base, "/predict/beta", {"inputs": [x]})
            assert code == 503
            assert body["model"] == "beta"
            assert body["scope"] == "model"
            assert body["breaker"] == "open"
            # /healthz flips to shedding and names the shedding model
            health = json.loads(urllib.request.urlopen(
                base + "/healthz", timeout=10).read())
            assert health["status"] == "shedding"
            assert health["shedding_models"] == ["beta"]
            assert health["models"]["alpha"]["breaker"] == "closed"
            # /models carries the per-tenant platform stats
            models = json.loads(urllib.request.urlopen(
                base + "/models", timeout=10).read())["models"]
            assert models["beta"]["breaker"] == "open"
            # alpha kept serving through beta's whole episode
            code, _ = _post(base, "/predict/alpha", {"inputs": [x]})
            assert code == 200
        finally:
            srv.stop()


# --- metrics / UI -----------------------------------------------------------

def test_platform_metrics_and_ui_surfaces(tmp_path):
    reg = ModelRegistry(tmp_path)
    v1 = _mlp(seed=1, hidden=18)
    reg.publish("mtr", v1)
    reg.publish("mtr", _bump(v1))
    x = _x()
    plat = ModelPlatform(reg, seed=1)
    try:
        plat.deploy("mtr", version=1, config=_cfg())
        plat.predict("mtr", x)
        snap = REGISTRY.snapshot()
        # per-tenant serving series (model label) + platform gauges
        assert snap[
            'dl4j_serving_requests_total{model="mtr",status="ok"}'] >= 1
        assert 'dl4j_platform_queue_depth{model="mtr"}' in snap
        assert 'dl4j_platform_warmup_compiles{model="mtr"}' in snap
        plat.swap("mtr", 2)
        snap = REGISTRY.snapshot(run_collectors=False)
        assert snap['dl4j_platform_swap_total{model="mtr"}'] >= 1
        # UI panel + /platform endpoint
        from deeplearning4j_tpu.ui.server import UIServer

        ui = UIServer()
        html = ui.render_html()
        assert "Serving platform" in html and "mtr" in html
        port = ui.start(port=0)
        try:
            rows = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{port}/platform", timeout=10).read())
            assert any("mtr" in p for p in rows)
        finally:
            ui.stop()
    finally:
        plat.close()


# --- generation tenants -----------------------------------------------------

def test_generation_tenant_deploy_and_generate():
    from deeplearning4j_tpu.parallel.generation import GenerationConfig
    from deeplearning4j_tpu.zoo.graphs import TransformerEncoder

    lm = TransformerEncoder(vocab_size=16, embed_dim=8, n_heads=2,
                            n_layers=1, max_len=16, causal=True,
                            lm_head=True, seed=5)
    with ModelPlatform(seed=1) as plat:
        out = plat.deploy_generation(
            "lm", model=lm,
            config=GenerationConfig(max_batch=2, fused_steps=2,
                                    kv_bucket_min=8, prompt_bucket_min=4))
        assert out["model"] == "lm"
        toks = plat.generate("lm", [1, 2, 3], max_new_tokens=4)
        assert len(toks) >= 1
        # named tenant: model-labeled decode series + serving:<name>
        # breaker visible in the aggregated /health view
        snap = REGISTRY.snapshot(run_collectors=False)
        assert snap[
            'dl4j_decode_requests_total{model="lm",status="ok"}'] >= 1
        assert "serving:lm" in resilience.status()["circuit_breakers"]
        assert plat.stats()["lm"]["generation"]["queue_depth"] == 0
        with pytest.raises(UnknownModelError, match="generation model"):
            plat.generate("nope", [1, 2])
