"""K-step fused training driver (round 11): the lax.scan multi-step
dispatch must be invisible to everything but the host-dispatch bill —
K=1 and K=4 train bit-identically on the same batch stream, listeners
and counters keep K=1 semantics, health guards keep their no-extra-sync
property with super-step remediation granularity, the AOT cache keys K,
and TrainingSession kill-and-resume stays bit-identical under
``fused_steps``."""

import numpy as np
import pytest

import jax

from deeplearning4j_tpu import telemetry
from deeplearning4j_tpu.conf import Activation, InputType
from deeplearning4j_tpu.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.conf.losses import LossMCXENT
from deeplearning4j_tpu.conf.multilayer import (
    BackpropType,
    NeuralNetConfiguration,
)
from deeplearning4j_tpu.conf.updaters import Adam
from deeplearning4j_tpu.datasets.dataset import DataSet, MultiDataSet
from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
from deeplearning4j_tpu.datasets.prefetch import (
    DeviceRingIterator,
    StackBatchIterator,
    stack_batch_group,
)
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.optimize import aot_cache
from deeplearning4j_tpu.optimize.listeners import (
    CollectScoresListener,
    PerformanceListener,
)
from deeplearning4j_tpu.telemetry import REGISTRY, flightrec, health

pytestmark = pytest.mark.fused

N_IN, N_OUT = 5, 3


@pytest.fixture(autouse=True)
def _clean_globals():
    """Telemetry spans / health mode / recorder are process-global."""
    telemetry.spans.disable()
    telemetry.reset()
    health.disable()
    health.MONITOR.reset()
    flightrec.RECORDER.disable().reset()
    REGISTRY.reset()
    yield
    telemetry.spans.disable()
    telemetry.reset()
    health.disable()
    health.MONITOR.reset()
    flightrec.RECORDER.disable().reset()
    REGISTRY.reset()


def _conf(width=16, seed=42):
    return (NeuralNetConfiguration.builder().seed(seed)
            .updater(Adam(0.01))
            .list()
            .layer(DenseLayer(n_out=width, activation=Activation.TANH))
            .layer(OutputLayer(n_out=N_OUT, activation=Activation.SOFTMAX,
                               loss_fn=LossMCXENT()))
            .set_input_type(InputType.feed_forward(N_IN))
            .build())


def _graph_conf(width=16, seed=42):
    return (NeuralNetConfiguration.builder().seed(seed)
            .updater(Adam(0.01))
            .graph_builder()
            .add_inputs("in")
            .add_layer("h", DenseLayer(n_out=width,
                                       activation=Activation.TANH), "in")
            .add_layer("out", OutputLayer(n_out=N_OUT,
                                          activation=Activation.SOFTMAX,
                                          loss_fn=LossMCXENT()), "h")
            .set_outputs("out")
            .set_input_types(InputType.feed_forward(N_IN))
            .build())


def _batches(n=8, batch=8, seed=0, poison=None):
    rng = np.random.default_rng(seed)
    out = []
    for i in range(n):
        x = rng.normal(size=(batch, N_IN)).astype(np.float32)
        if poison is not None and i == poison:
            x[0, 0] = np.nan
        y = np.eye(N_OUT, dtype=np.float32)[rng.integers(0, N_OUT, batch)]
        out.append(DataSet(x, y))
    return out


def _iterator(**kw):
    return ListDataSetIterator(_batches(**kw))


def _leaves(net):
    return [np.asarray(l) for l in jax.tree_util.tree_leaves(
        (net.params, net.state, net.opt_state))]


def _assert_bit_identical(a, b):
    la, lb = _leaves(a), _leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(x, y)


# ---------------------------------------------------------------------------
# stacking iterator mechanics
# ---------------------------------------------------------------------------

def test_stack_group_uniform_ragged_and_tail():
    it = DeviceRingIterator(_iterator(n=7), stack_batches=3)
    items = list(it)
    ks = [int(getattr(d, "fused_stack", 0)) for d in items]
    assert ks == [3, 3, 0]                      # 2 stacks + ragged tail
    assert np.shape(items[0].features) == (3, 8, N_IN)
    assert np.shape(items[2].features) == (8, N_IN)


def test_stack_group_nonuniform_falls_back():
    dss = _batches(n=2) + [DataSet(np.ones((4, N_IN), np.float32),
                                   np.ones((4, N_OUT), np.float32))]
    assert stack_batch_group(dss) is None       # ragged batch dims
    items = list(StackBatchIterator(ListDataSetIterator(dss), 3))
    assert [int(getattr(d, "fused_stack", 0)) for d in items] == [0, 0, 0]


def test_skip_staging_fast_forward_pays_no_transfers():
    """A resuming session's replay fast-forward discards items — the
    ring must not device-stage them (same yield positions either way)."""
    dss = _batches(n=8)
    it = DeviceRingIterator(ListDataSetIterator(dss), stack_batches=2)
    it.skip_staging(2)
    items = list(it)
    assert len(items) == 4
    assert it.staged_count == 2                 # only the live stacks
    # the skipped yields are un-staged AND un-stacked placeholders
    # (first batch's arrays by identity — no K-batch host copies)
    assert items[0].features is dss[0].features
    assert getattr(items[0], "fused_stack", 0) == 2
    # the hint is one-shot: a fresh epoch stages everything again
    it.reset()
    assert it.staged_count == 2 and list(it) and it.staged_count == 6


def test_stack_group_multidataset():
    mds = [MultiDataSet(features=[d.features], labels=[d.labels])
           for d in _batches(n=2)]
    stacked = stack_batch_group(mds)
    assert stacked.fused_stack == 2
    assert np.shape(stacked.features[0]) == (2, 8, N_IN)


# ---------------------------------------------------------------------------
# numerics: K=1 vs K=4 bit-identical
# ---------------------------------------------------------------------------

def test_fused_k4_bit_identical_multilayer():
    n1 = MultiLayerNetwork(_conf()).init()
    n1.fit(_iterator(), epochs=2)
    n4 = MultiLayerNetwork(_conf()).init()
    n4.fit(_iterator(), epochs=2, fused_steps=4)
    _assert_bit_identical(n1, n4)
    assert n1.iteration == n4.iteration == 16
    assert n1.score_value == n4.score_value


def test_fused_k4_bit_identical_graph():
    n1 = ComputationGraph(_graph_conf()).init()
    n1.fit(_iterator(), epochs=2)
    n4 = ComputationGraph(_graph_conf()).init()
    n4.fit(_iterator(), epochs=2, fused_steps=4)
    _assert_bit_identical(n1, n4)
    assert n1.iteration == n4.iteration == 16


def test_fused_wrapper_exact_spmd_bit_identical():
    from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper

    n1 = MultiLayerNetwork(_conf()).init()
    ParallelWrapper(n1, workers=2, prefetch_buffer=0).fit(
        _iterator(), epochs=2)
    n4 = MultiLayerNetwork(_conf()).init()
    ParallelWrapper(n4, workers=2, prefetch_buffer=0, fused_steps=4).fit(
        _iterator(), epochs=2)
    for x, y in zip(_leaves(n1), _leaves(n4)):
        np.testing.assert_array_equal(x, y)
    assert n1.iteration == n4.iteration == 16


def test_fused_wrapper_mode_validation():
    from deeplearning4j_tpu.parallel.wrapper import (
        ParallelWrapper,
        TrainingMode,
    )

    net = MultiLayerNetwork(_conf()).init()
    with pytest.raises(ValueError, match="exact SHARED_GRADIENTS"):
        ParallelWrapper(net, workers=2, fused_steps=4,
                        training_mode=TrainingMode.AVERAGING)
    with pytest.raises(ValueError, match="exact SHARED_GRADIENTS"):
        ParallelWrapper(net, workers=2, fused_steps=4,
                        gradient_bucket_mb=1.0)


def test_fused_tbptt_refuses():
    conf = (NeuralNetConfiguration.builder().seed(1).updater(Adam(0.01))
            .list()
            .layer(DenseLayer(n_out=4, activation=Activation.TANH))
            .layer(OutputLayer(n_out=N_OUT, activation=Activation.SOFTMAX,
                               loss_fn=LossMCXENT()))
            .set_input_type(InputType.feed_forward(N_IN))
            .backprop_type(BackpropType.TRUNCATED_BPTT, 4, 4)
            .build())
    net = MultiLayerNetwork(conf).init()
    with pytest.raises(ValueError, match="STANDARD backprop only"):
        net.fit(_iterator(), fused_steps=4)


# ---------------------------------------------------------------------------
# listeners / counters keep K=1 semantics
# ---------------------------------------------------------------------------

def test_listeners_receive_k_per_step_losses():
    c1, c4 = CollectScoresListener(), CollectScoresListener()
    n1 = MultiLayerNetwork(_conf()).init()
    n1.set_listeners(c1)
    n1.fit(_iterator(), epochs=1)
    n4 = MultiLayerNetwork(_conf()).init()
    n4.set_listeners(c4)
    n4.fit(_iterator(), epochs=1, fused_steps=4)
    assert c4.iterations == c1.iterations == list(range(8))
    np.testing.assert_array_equal(c4.scores, c1.scores)


def test_performance_listener_counts_match_k1(capsys):
    """K steps arrive per host callback: iteration counts and the
    examples/sec basis (per-STEP batch size, not K*B) must match K=1."""
    perf = PerformanceListener(frequency=4)
    net = MultiLayerNetwork(_conf()).init()
    net.set_listeners(perf)
    telemetry.enable()
    net.fit(_iterator(n=8, batch=8), epochs=1, fused_steps=4)
    telemetry.disable()
    assert net.last_batch_size == 8             # per-step rows, not K*B
    assert net.iteration == 8
    assert perf.last_batches_per_sec is not None
    assert perf.last_examples_per_sec == pytest.approx(
        perf.last_batches_per_sec * 8)
    snap = REGISTRY.snapshot(run_collectors=False)
    assert snap['dl4j_training_steps_total{path="multilayer"}'] == 8.0
    assert snap['dl4j_training_examples_total{path="multilayer"}'] == 64.0


def test_host_gap_spans_recorded_with_step_weights():
    telemetry.enable()
    net = MultiLayerNetwork(_conf()).init()
    net.fit(_iterator(), epochs=1, fused_steps=4)
    telemetry.disable()
    gaps = [e for e in telemetry.events()
            if e["name"] == telemetry.PHASE_HOST_GAP]
    assert len(gaps) == 2                       # one per super-step
    assert all(e["attrs"]["steps"] == 4 for e in gaps)
    assert telemetry.PHASE_HOST_GAP in telemetry.PHASES


# ---------------------------------------------------------------------------
# AOT cache: K joins the key, refits never recompile
# ---------------------------------------------------------------------------

def test_fused_zero_recompiles_across_refits():
    # unique width: the AOT cache is process-global and conf-keyed
    net = MultiLayerNetwork(_conf(width=23)).init()
    net.fit(_iterator(), epochs=1, fused_steps=4)
    st0 = aot_cache.stats()
    net.fit(_iterator(), epochs=1, fused_steps=4)
    st1 = aot_cache.stats()
    assert st1["misses"] == st0["misses"]       # zero recompiles on refit
    assert st1["hits"] > st0["hits"]


def test_fused_k_joins_cache_key():
    net = MultiLayerNetwork(_conf(width=29)).init()
    net.fit(_iterator(), epochs=1, fused_steps=4)
    e0 = aot_cache.stats()["entries"]
    net2 = MultiLayerNetwork(_conf(width=29)).init()
    net2.fit(_iterator(), epochs=1, fused_steps=2)
    # a different K compiles its own executable even though the graph
    # key (same conf) and the per-step math are identical
    assert aot_cache.stats()["entries"] > e0


# ---------------------------------------------------------------------------
# health guards: in-scan, super-step granularity
# ---------------------------------------------------------------------------

def test_fused_skip_step_bit_identical_to_k1_and_counts():
    health.configure(policy=health.AnomalyPolicy.SKIP_STEP,
                     record_flights=False)
    n1 = MultiLayerNetwork(_conf()).init()
    n1.fit(ListDataSetIterator(_batches(poison=5)), epochs=1)
    r1 = health.report()
    health.configure(policy=health.AnomalyPolicy.SKIP_STEP,
                     record_flights=False)
    n4 = MultiLayerNetwork(_conf()).init()
    n4.fit(ListDataSetIterator(_batches(poison=5)), epochs=1,
           fused_steps=4)
    r4 = health.report()
    _assert_bit_identical(n1, n4)               # in-graph skip per step
    assert r1["nonfinite_steps"] == r4["nonfinite_steps"] == 1
    assert r1["skipped_steps"] == r4["skipped_steps"] == 1
    assert r4["last_anomaly_step"] == r1["last_anomaly_step"] == 6


def test_fused_halt_surfaces_offending_step_index():
    health.configure(policy=health.AnomalyPolicy.HALT,
                     record_flights=False)
    net = MultiLayerNetwork(_conf()).init()
    with pytest.raises(health.DivergenceError) as exc:
        net.fit(ListDataSetIterator(_batches(poison=5)), epochs=1,
                fused_steps=4)
    # batch 5 (0-based) = monitor step 6 = row 2/4 of super-step 2
    assert exc.value.step == 6
    assert "2/4 of the fused super-step" in str(exc.value)


def test_fused_rollback_restores_at_superstep_granularity():
    health.configure(policy=health.AnomalyPolicy.ROLLBACK,
                     snapshot_every=1, record_flights=False)
    net = MultiLayerNetwork(_conf()).init()
    net.fit(ListDataSetIterator(_batches(poison=5)), epochs=1,
            fused_steps=4)
    rep = health.report()
    assert rep["rollbacks"] == 1
    # the restore rolled the whole poisoned super-step back to the
    # last-good boundary; training continued and params are finite
    assert all(np.isfinite(l).all() for l in _leaves(net))


# ---------------------------------------------------------------------------
# resilience: kill-and-resume bit-identical under fused_steps
# ---------------------------------------------------------------------------

def test_session_kill_mid_run_resumes_bit_identical(tmp_path):
    from deeplearning4j_tpu.resilience import TrainingSession
    from deeplearning4j_tpu.resilience.faults import FaultPlan

    ref = MultiLayerNetwork(_conf()).init()
    ref.fit(_iterator(), epochs=2, fused_steps=4)

    sess = TrainingSession(MultiLayerNetwork(_conf()).init(),
                           str(tmp_path), snapshot_every_n_iterations=4)
    plan = FaultPlan(seed=1).inject("train.step", on_calls=[3])
    with plan.armed():
        sess.fit(_iterator(), epochs=2, fused_steps=4)
    assert plan.fired("train.step") == 1
    assert sess.model.epoch == 2
    _assert_bit_identical(ref, sess.model)
    # snapshots land on K-aligned boundaries only
    assert all(s["iteration"] % 4 == 0 for s in sess.snapshots())
