"""Arbiter hyperparameter search + RL4J DQN (reference: arbiter optimize
tests, rl4j QLearning tests)."""

import math

import numpy as np
import pytest

from deeplearning4j_tpu.arbiter import (
    BooleanSpace,
    ContinuousParameterSpace,
    DataSetIteratorProvider,
    DataSetLossScoreFunction,
    DiscreteParameterSpace,
    EvaluationScoreFunction,
    FixedValue,
    GridSearchCandidateGenerator,
    IntegerParameterSpace,
    LocalOptimizationRunner,
    MaxCandidatesCondition,
    OptimizationConfiguration,
    RandomSearchGenerator,
)
from deeplearning4j_tpu.conf import Activation, InputType
from deeplearning4j_tpu.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.conf.losses import LossMCXENT
from deeplearning4j_tpu.conf.multilayer import NeuralNetConfiguration
from deeplearning4j_tpu.conf.updaters import Adam
from deeplearning4j_tpu.datasets.iterators import ArrayDataSetIterator
from deeplearning4j_tpu.rl4j import (
    CartPole,
    QLearningConfiguration,
    QLearningDiscreteDense,
    SimpleToyMDP,
)


# --------------------------------------------------------------------------
# parameter spaces + generators
# --------------------------------------------------------------------------

def test_spaces_sample_and_grid():
    rng = np.random.default_rng(0)
    c = ContinuousParameterSpace(0.1, 1.0)
    assert 0.1 <= c.sample(rng) <= 1.0
    cl = ContinuousParameterSpace(1e-4, 1e-1, log_scale=True)
    assert 1e-4 <= cl.sample(rng) <= 1e-1
    assert len(cl.grid(3)) == 3
    i = IntegerParameterSpace(2, 5)
    assert i.sample(rng) in (2, 3, 4, 5)
    d = DiscreteParameterSpace("a", "b")
    assert d.sample(rng) in ("a", "b")
    assert BooleanSpace().grid(9) == [True, False]
    assert FixedValue(7).sample(rng) == 7


def test_grid_generator_cartesian():
    gen = GridSearchCandidateGenerator(
        {"lr": ContinuousParameterSpace(0.1, 0.3),
         "n": DiscreteParameterSpace(4, 8)}, discretization_count=3)
    combos = list(gen.candidates())
    assert len(combos) == 6
    assert {c["n"] for c in combos} == {4, 8}


def test_random_generator_stream():
    gen = RandomSearchGenerator({"lr": ContinuousParameterSpace(0, 1)},
                                seed=1)
    it = gen.candidates()
    a, b = next(it), next(it)
    assert a != b


# --------------------------------------------------------------------------
# end-to-end search
# --------------------------------------------------------------------------

def _data(n=96, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 4)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[(x[:, 0] > 0).astype(int)]
    return x, y


def _builder(lr=1e-2, n_hidden=8):
    return (NeuralNetConfiguration.builder()
            .seed(1)
            .updater(Adam(lr))
            .list()
            .layer(DenseLayer(n_out=int(n_hidden),
                              activation=Activation.TANH))
            .layer(OutputLayer(n_out=2, activation=Activation.SOFTMAX,
                               loss_fn=LossMCXENT()))
            .set_input_type(InputType.feed_forward(4))
            .build())


def test_local_runner_finds_learnable_candidate():
    x, y = _data()
    provider = DataSetIteratorProvider(
        ArrayDataSetIterator(x, y, batch=32),
        ArrayDataSetIterator(x, y, batch=32))
    config = OptimizationConfiguration(
        candidate_generator=RandomSearchGenerator(
            {"lr": ContinuousParameterSpace(1e-3, 1e-1, log_scale=True),
             "n_hidden": IntegerParameterSpace(4, 16)}, seed=7),
        data_provider=provider,
        score_function=EvaluationScoreFunction("accuracy"),
        termination_conditions=[MaxCandidatesCondition(4)],
        epochs_per_candidate=8)
    result = LocalOptimizationRunner(config, _builder).execute()
    assert len(result.results) == 4
    assert result.best_score() > 0.6
    assert set(result.best_values()) == {"lr", "n_hidden"}
    assert result.best_model() is not None


def test_loss_score_function_minimizes():
    x, y = _data()
    provider = DataSetIteratorProvider(
        ArrayDataSetIterator(x, y, batch=32),
        ArrayDataSetIterator(x, y, batch=32))
    config = OptimizationConfiguration(
        candidate_generator=GridSearchCandidateGenerator(
            {"lr": DiscreteParameterSpace(1e-2, 1e-7)},
            discretization_count=2),
        data_provider=provider,
        score_function=DataSetLossScoreFunction(),
        termination_conditions=[MaxCandidatesCondition(10)],
        epochs_per_candidate=10)
    result = LocalOptimizationRunner(config, _builder).execute()
    # the real learning rate must beat the degenerate one on loss
    assert result.best_values()["lr"] == pytest.approx(1e-2)


def test_bad_candidate_does_not_kill_run():
    x, y = _data()
    provider = DataSetIteratorProvider(
        ArrayDataSetIterator(x, y, batch=32),
        ArrayDataSetIterator(x, y, batch=32))

    def builder(n_hidden):
        if n_hidden == 0:
            raise ValueError("boom")
        return _builder(n_hidden=n_hidden)

    config = OptimizationConfiguration(
        candidate_generator=GridSearchCandidateGenerator(
            {"n_hidden": DiscreteParameterSpace(0, 8)}),
        data_provider=provider,
        score_function=EvaluationScoreFunction(),
        termination_conditions=[MaxCandidatesCondition(10)])
    result = LocalOptimizationRunner(config, builder).execute()
    assert math.isnan(result.results[0].score)
    assert result.best_values()["n_hidden"] == 8


def test_requires_termination_condition():
    with pytest.raises(ValueError):
        OptimizationConfiguration(None, None, None, [])


# --------------------------------------------------------------------------
# RL4J
# --------------------------------------------------------------------------

def test_replay_memory():
    from deeplearning4j_tpu.rl4j import ReplayMemory

    mem = ReplayMemory(4, seed=0)
    for i in range(6):
        mem.store(np.asarray([i], np.float32), i % 2, float(i),
                  np.asarray([i + 1], np.float32), 0.0)
    assert len(mem) == 4  # bounded FIFO
    s, a, r, s2, d = mem.sample(8)
    assert s.shape == (8, 1) and r.min() >= 2.0  # oldest evicted


def test_dqn_learns_toy_chain():
    cfg = QLearningConfiguration(
        seed=7, max_step=1500, max_epoch_step=30, batch_size=32,
        update_start=50, target_dqn_update_freq=50, epsilon_nb_step=800,
        gamma=0.95, learning_rate=5e-3)
    dqn = QLearningDiscreteDense(SimpleToyMDP(length=8), cfg,
                                 hidden=[32])
    dqn.train()
    # optimal policy always advances: greedy return == chain length
    assert dqn.play(episodes=3) >= 7.0
    assert dqn.epsilon() == pytest.approx(cfg.min_epsilon)


def test_cartpole_env_dynamics():
    env = CartPole(max_steps=50, seed=1)
    obs = env.reset()
    assert obs.shape == (4,)
    total = 0
    done = False
    while not done:
        _, r, done = env.step(1)
        total += r
    assert 1 <= total <= 50  # constant action tips the pole over


def test_dqn_cartpole_improves():
    cfg = QLearningConfiguration(
        seed=3, max_step=6000, max_epoch_step=200, batch_size=64,
        update_start=200, target_dqn_update_freq=100, epsilon_nb_step=3000,
        learning_rate=5e-4)
    dqn = QLearningDiscreteDense(CartPole(max_steps=200, seed=3), cfg)
    dqn.train()
    trained_score = dqn.play(episodes=3)
    # early episodes run at epsilon ~1 == the random-policy baseline
    random_score = float(np.mean(dqn.episode_rewards[:5]))
    assert trained_score > random_score
    assert trained_score > 50


# --------------------------------------------------------------------------
# async learners (A3C, n-step Q) + policies
# --------------------------------------------------------------------------

def test_a3c_learns_toy_chain():
    from deeplearning4j_tpu.rl4j import A3CConfiguration, A3CDiscreteDense
    cfg = A3CConfiguration(seed=5, max_step=6000, max_epoch_step=20,
                           num_threads=2, nstep=5, learning_rate=3e-3)
    a3c = A3CDiscreteDense(lambda tid: SimpleToyMDP(length=8), cfg,
                           hidden=[32])
    a3c.train()
    assert a3c.shared.update_count > 0
    assert a3c.play(episodes=3) >= 7.0


def test_async_nstep_q_learns_toy_chain():
    from deeplearning4j_tpu.rl4j import (
        AsyncNStepQLearningDiscreteDense,
        AsyncQLearningConfiguration,
    )
    cfg = AsyncQLearningConfiguration(
        seed=7, max_step=6000, max_epoch_step=20, num_threads=2, nstep=5,
        learning_rate=3e-3, epsilon_nb_step=2500,
        target_dqn_update_freq=200)
    ql = AsyncNStepQLearningDiscreteDense(
        lambda tid: SimpleToyMDP(length=8), cfg, hidden=[32])
    ql.train()
    assert ql.play(episodes=3) >= 7.0


def test_policies():
    from deeplearning4j_tpu.rl4j import (
        A3CConfiguration,
        A3CDiscreteDense,
        ACPolicy,
        DQNPolicy,
        EpsGreedy,
        QLearningConfiguration,
        QLearningDiscreteDense,
    )
    mdp = SimpleToyMDP(length=5)
    dqn = QLearningDiscreteDense(mdp, QLearningConfiguration(max_step=1),
                                 hidden=[8])
    pol = DQNPolicy(dqn.params)
    assert pol.next_action(mdp.reset()) in (0, 1)
    assert isinstance(pol.play(SimpleToyMDP(length=3), episodes=1), float)

    a3c = A3CDiscreteDense(lambda tid: SimpleToyMDP(length=5),
                           A3CConfiguration(max_step=1), hidden=[8])
    acp = ACPolicy(a3c.params, rng=np.random.default_rng(0))
    assert acp.next_action(mdp.reset()) in (0, 1)
    greedy = ACPolicy(a3c.params)
    assert greedy.next_action(mdp.reset()) in (0, 1)

    eps = EpsGreedy(pol, action_size=2, min_epsilon=0.1,
                    epsilon_nb_step=10, rng=np.random.default_rng(0))
    acts = [eps.next_action(mdp.reset()) for _ in range(20)]
    assert set(acts) <= {0, 1}
    assert eps.epsilon() == pytest.approx(0.1)


def test_search_report_renders(tmp_path):
    from deeplearning4j_tpu.arbiter import CandidateResult, OptimizationResult

    results = [CandidateResult(i, {"lr": 0.1 / (i + 1)}, 1.0 / (i + 1), None)
               for i in range(5)]
    results.append(CandidateResult(5, {"lr": 0.0}, float("nan"), None,
                                   exception=RuntimeError("diverged")))
    # diverged WITHOUT an exception: NaN score must not blank the chart
    results.append(CandidateResult(6, {"lr": 9.9}, float("nan"), None))
    res = OptimizationResult(results[4], results, minimize=True)
    path = res.render(str(tmp_path / "search.html"))
    text = open(path).read()
    assert "Candidate score" in text and "<svg" in text
    assert "nan" not in text.split("<svg")[1].split("</svg>")[0]
    assert "2 failed" in text
    assert "best score 0.2" in text
    assert "lr" in text
