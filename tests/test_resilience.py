"""Fault-tolerant execution layer chaos suite (docs/resilience.md).

Every fault here is ARMED WITH A FIXED SEED / exact invocation index, so
a failure replays exactly (`make chaos-smoke`). The recovery invariants
under test are the PR's acceptance bar:

- kill training mid-run via an armed fault -> ``TrainingSession``
  resumes and final params are bit-identical to an uninterrupted run;
- injected serving-launch failures trip the circuit breaker open, then
  recover through half-open probes with no dispatcher deadlock and all
  pending futures resolved (no hung client);
- crash-mid-write checkpointing never leaves a temp file behind, never
  references a half-written zip from checkpoint.csv, and the prior
  checkpoint stays loadable.

Counter assertions read DELTAS: the telemetry registry is process-global
(the autouse fixture resets it, but helpers registered by other modules
may fire during a test).
"""

import errno
import glob
import os
import threading
import time

import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.conf import Activation, InputType
from deeplearning4j_tpu.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.conf.losses import LossMCXENT
from deeplearning4j_tpu.conf.multilayer import NeuralNetConfiguration
from deeplearning4j_tpu.conf.updaters import Adam
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.optimize.checkpoint import CheckpointListener
from deeplearning4j_tpu.parallel.batcher import (
    BatchingConfig,
    InferenceEngine,
    LaunchTimeoutError,
)
from deeplearning4j_tpu.resilience import (
    CircuitBreaker,
    CircuitOpenError,
    FaultPlan,
    InjectedFault,
    RetryPolicy,
    TrainingSession,
    status,
)
from deeplearning4j_tpu.resilience import faults
from deeplearning4j_tpu.resilience.breaker import CLOSED, HALF_OPEN, OPEN
from deeplearning4j_tpu.telemetry import REGISTRY
from deeplearning4j_tpu.util import params as params_util

pytestmark = pytest.mark.resilience


@pytest.fixture(autouse=True)
def _clean_resilience():
    """No plan stays armed across tests (the arm slot is process-global)
    and every test reads metrics from a clean registry."""
    faults._ACTIVE = None
    REGISTRY.reset()
    yield
    faults._ACTIVE = None
    REGISTRY.reset()


def counter_value(name, **labels):
    return REGISTRY.counter(name, **labels).snapshot_value()


# ---------------------------------------------------------------------------
# fault injection (resilience/faults.py)
# ---------------------------------------------------------------------------

def test_fault_point_disarmed_is_identity():
    a = np.ones(3, np.float32)
    assert faults.fault_point("train.step", a) is a
    assert faults.fault_point("nonexistent.site") is None


def test_on_calls_fires_on_exact_invocations():
    plan = FaultPlan(seed=7).inject("train.step", on_calls=[2, 4])
    fired = []
    with plan.armed():
        for i in range(1, 6):
            try:
                faults.fault_point("train.step")
            except InjectedFault as e:
                fired.append(i)
                assert e.site == "train.step"
                assert e.invocation == i
    assert fired == [2, 4]
    assert plan.invocations("train.step") == 5
    assert plan.fired("train.step") == 2


def test_probability_stream_is_seed_deterministic():
    def pattern(seed):
        plan = FaultPlan(seed=seed).inject("train.step", probability=0.3)
        hits = []
        with plan.armed():
            for i in range(60):
                try:
                    faults.fault_point("train.step")
                except InjectedFault:
                    hits.append(i)
        return hits

    a, b, c = pattern(42), pattern(42), pattern(43)
    assert a == b          # same seed -> identical firing sequence
    assert a != c          # different seed -> different stream
    assert 5 < len(a) < 35  # sanity: roughly p=0.3 of 60


def test_corrupt_action_nan_poisons_floats_only():
    plan = FaultPlan().inject("ingest.device_put", action="corrupt")
    f32 = np.arange(4, dtype=np.float32)
    u8 = np.arange(4, dtype=np.uint8)
    dev = jnp.arange(3, dtype=jnp.float32)
    with plan.armed():
        out = faults.fault_point("ingest.device_put", f32)
        assert np.isnan(out[0]) and not np.isnan(out[1:]).any()
        assert not np.isnan(f32).any()  # poisons a COPY
        assert faults.fault_point("ingest.device_put", u8) is u8
        dout = faults.fault_point("ingest.device_put", dev)
        assert isinstance(dout, jnp.ndarray) and np.isnan(
            np.asarray(dout)[0])


def test_delay_action_sleeps_then_passes_through():
    plan = FaultPlan().inject("serving.launch", action="delay",
                              delay_s=0.05, max_fires=1)
    with plan.armed():
        t0 = time.monotonic()
        assert faults.fault_point("serving.launch", "v") == "v"
        assert time.monotonic() - t0 >= 0.045
        t0 = time.monotonic()
        faults.fault_point("serving.launch")  # max_fires exhausted
        assert time.monotonic() - t0 < 0.04


def test_custom_exception_factory_and_counter():
    plan = FaultPlan().inject(
        "checkpoint.write", on_calls=[1],
        exc=lambda: OSError(errno.ENOSPC, "No space left on device"))
    with plan.armed():
        with pytest.raises(OSError) as ei:
            faults.fault_point("checkpoint.write")
    assert ei.value.errno == errno.ENOSPC
    assert counter_value("dl4j_faults_injected_total",
                         site="checkpoint.write", action="raise") == 1


def test_only_one_plan_armed_per_process():
    p1, p2 = FaultPlan(), FaultPlan()
    with p1.armed():
        with pytest.raises(RuntimeError, match="already armed"):
            p2.arm()
    # p1's context exit disarmed: p2 can now arm
    with p2.armed():
        assert faults.active_plan() is p2
    assert faults.active_plan() is None


# ---------------------------------------------------------------------------
# retry engine (resilience/retry.py)
# ---------------------------------------------------------------------------

def test_backoff_is_pure_function_of_seed_name_attempt():
    a = RetryPolicy(seed=5, name="op", base_delay_s=0.1, jitter=0.5)
    b = RetryPolicy(seed=5, name="op", base_delay_s=0.1, jitter=0.5)
    assert [a.backoff_s(k) for k in (1, 2, 3)] == \
        [b.backoff_s(k) for k in (1, 2, 3)]
    c = RetryPolicy(seed=6, name="op", base_delay_s=0.1, jitter=0.5)
    assert a.backoff_s(1) != c.backoff_s(1)
    # jitter=0: exact exponential with cap
    p = RetryPolicy(base_delay_s=0.1, multiplier=2.0, max_delay_s=0.3,
                    jitter=0.0)
    assert [p.backoff_s(k) for k in (1, 2, 3, 4)] == \
        pytest.approx([0.1, 0.2, 0.3, 0.3])


def test_retry_recovers_from_transient_and_counts():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise OSError(errno.EINTR, "interrupted")
        return "ok"

    slept = []
    p = RetryPolicy(max_attempts=3, base_delay_s=0.01, name="t")
    before = counter_value("dl4j_retries_total", op="t")
    assert p.call(flaky, sleep=slept.append) == "ok"
    assert len(calls) == 3 and len(slept) == 2
    assert counter_value("dl4j_retries_total", op="t") - before == 2


def test_non_retryable_propagates_immediately():
    calls = []

    def bad():
        calls.append(1)
        raise ValueError("model bug")

    p = RetryPolicy(max_attempts=5)
    with pytest.raises(ValueError):
        p.call(bad, sleep=lambda s: pytest.fail("must not sleep"))
    assert len(calls) == 1


def test_retry_exhaustion_raises_last_error():
    p = RetryPolicy(max_attempts=3, base_delay_s=0.0)
    calls = []

    def always():
        calls.append(1)
        raise TimeoutError("down")

    with pytest.raises(TimeoutError):
        p.call(always, sleep=lambda s: None)
    assert len(calls) == 3


def test_deadline_outranks_retry_budget():
    p = RetryPolicy(max_attempts=5, base_delay_s=0.5, jitter=0.0)

    def always():
        raise OSError("transient")

    t0 = time.monotonic()
    with pytest.raises(OSError):
        # next backoff (0.5s) would land past the deadline: no sleep,
        # the error propagates at once
        p.call(always, deadline=time.monotonic() + 0.05,
               sleep=lambda s: pytest.fail("slept past the deadline"))
    assert time.monotonic() - t0 < 0.2


# ---------------------------------------------------------------------------
# checkpoint write/load hardening
# ---------------------------------------------------------------------------

def _ckpt_net(seed=11):
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Adam(0.01))
            .list()
            .layer(DenseLayer(n_out=9, activation=Activation.RELU))
            .layer(OutputLayer(n_out=3, activation=Activation.SOFTMAX,
                               loss_fn=LossMCXENT()))
            .set_input_type(InputType.feed_forward(4)).build())
    return MultiLayerNetwork(conf).init()


def _batches(seed=0, n_batches=6, rows=8, n_in=4, n_out=3):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n_batches):
        x = rng.normal(size=(rows, n_in)).astype(np.float32)
        y = np.eye(n_out, dtype=np.float32)[rng.integers(0, n_out, rows)]
        out.append((x, y))
    return out


def _no_tmp_files(directory):
    return glob.glob(os.path.join(directory, "*.tmp.*")) == []


def test_crash_mid_write_keeps_prior_checkpoint_loadable(tmp_path):
    net = _ckpt_net()
    lst = CheckpointListener(str(tmp_path), save_every_n_iterations=1)
    lst._save(net, 0, 0)
    first = np.asarray(net.params_flat())

    net.fit(*_batches(n_batches=1)[0])
    # ENOSPC on every attempt of the second save (invocation counting is
    # per-plan from arming: the hook fires once per write_model attempt
    # and CHECKPOINT_RETRY makes three) -> the save fails for good,
    # mid-zip-assembly = partial write
    plan = FaultPlan().inject(
        "checkpoint.write", on_calls=[1, 2, 3],
        exc=lambda: OSError(errno.ENOSPC, "No space left on device"))
    before = counter_value("dl4j_retries_total", op="checkpoint.write")
    with plan.armed():
        with pytest.raises(OSError):
            lst._save(net, 1, 0)
    assert plan.fired("checkpoint.write") == 3
    # the two scheduled retries were real (and counted)
    assert counter_value("dl4j_retries_total",
                         op="checkpoint.write") - before == 2
    # no half-written temp zip survives the crash
    assert _no_tmp_files(str(tmp_path))
    # checkpoint.csv never references the failed zip
    cps = lst.list_checkpoints()
    assert [c.number for c in cps] == [0]
    assert len(glob.glob(os.path.join(str(tmp_path), "*.zip"))) == 1
    # and the prior checkpoint still restores, digest-verified
    restored = lst.load_checkpoint()
    np.testing.assert_array_equal(
        np.asarray(restored.params_flat()), first)


def test_transient_write_fault_is_retried_to_success(tmp_path):
    net = _ckpt_net()
    lst = CheckpointListener(str(tmp_path), save_every_n_iterations=1)
    plan = FaultPlan().inject("checkpoint.write", on_calls=[1])
    with plan.armed():
        lst._save(net, 0, 0)  # attempt 1 faults, attempt 2 lands
    assert plan.fired("checkpoint.write") == 1
    cps = lst.list_checkpoints()
    assert len(cps) == 1 and cps[0].digest
    assert lst.verify(cps[0])
    assert _no_tmp_files(str(tmp_path))
    lst.load_checkpoint()  # loadable, digest-verified


def test_load_falls_back_to_last_good_on_corruption(tmp_path):
    net = _ckpt_net()
    lst = CheckpointListener(str(tmp_path), save_every_n_iterations=1)
    lst._save(net, 0, 0)
    good = np.asarray(net.params_flat())
    net.fit(*_batches(n_batches=1)[0])
    lst._save(net, 1, 0)
    # truncate the NEWEST zip: digest verification must reject it and
    # load must hand back the previous generation, not raise mid-restore
    newest = os.path.join(str(tmp_path), lst.list_checkpoints()[-1].filename)
    with open(newest, "r+b") as f:
        f.truncate(os.path.getsize(newest) // 2)
    restored = lst.load_checkpoint()
    np.testing.assert_array_equal(np.asarray(restored.params_flat()), good)
    # an EXPLICIT number disables the fallback: the caller asked for
    # exactly that state, silently substituting another would be wrong
    with pytest.raises(OSError, match="digest"):
        lst.load_checkpoint(number=1)


def test_pre_digest_rows_load_unverified(tmp_path):
    # rows written before the digest column existed have digest="" and
    # must keep loading exactly as they always did
    net = _ckpt_net()
    lst = CheckpointListener(str(tmp_path), save_every_n_iterations=1)
    lst._save(net, 0, 0)
    csv_path = os.path.join(str(tmp_path), "checkpoint.csv")
    with open(csv_path) as f:
        rows = [line.rsplit(",", 1)[0] for line in f.read().splitlines()]
    with open(csv_path, "w") as f:
        f.write("\n".join(rows) + "\n")
    cps = lst.list_checkpoints()
    assert cps[0].digest == ""
    assert lst.verify(cps[0])
    lst.load_checkpoint()


# ---------------------------------------------------------------------------
# circuit breaker (resilience/breaker.py)
# ---------------------------------------------------------------------------

class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def test_breaker_trips_on_consecutive_failures_and_recovers():
    clk = _Clock()
    b = CircuitBreaker(failure_threshold=3, recovery_timeout_s=10.0,
                       success_threshold=2, name="t1", clock=clk)
    assert b.state == CLOSED
    b.on_failure(); b.on_failure()
    assert b.state == CLOSED and b.allow()
    b.on_failure()                      # third consecutive: trip
    assert b.state == OPEN
    assert not b.allow()                # fail-fast shedding
    clk.t = 10.0                        # recovery timeout elapses
    assert b.state == HALF_OPEN
    assert b.allow()                    # the one probe ticket
    assert not b.allow()                # second caller: still shed
    b.on_success()
    assert b.state == HALF_OPEN         # needs success_threshold=2
    assert b.allow()                    # next probe admitted
    b.on_success()
    assert b.state == CLOSED and b.allow()
    assert b.tripped_total == 1


def test_failed_probe_reopens_and_restarts_clock():
    clk = _Clock()
    b = CircuitBreaker(failure_threshold=1, recovery_timeout_s=5.0,
                       name="t2", clock=clk)
    b.on_failure()
    clk.t = 5.0
    assert b.allow()                    # half-open probe
    b.on_failure()                      # probe fails: re-open
    assert b.state == OPEN
    clk.t = 9.0                         # clock restarted at 5.0
    assert not b.allow()
    clk.t = 10.0
    assert b.allow()


def test_failure_rate_trip_catches_steady_trickle():
    clk = _Clock()
    b = CircuitBreaker(failure_threshold=100, failure_rate=0.5,
                       window_size=10, name="t3", clock=clk)
    # alternate success/failure: never 100 consecutive, but 50% rate
    for _ in range(5):
        b.on_success(); b.on_failure()
    assert b.state == OPEN


def test_lost_probe_ticket_is_reissued():
    clk = _Clock()
    b = CircuitBreaker(failure_threshold=1, recovery_timeout_s=2.0,
                       name="t4", clock=clk)
    b.on_failure()
    clk.t = 2.0
    assert b.allow()        # probe issued; its waiter then vanishes
    assert not b.allow()
    clk.t = 4.0             # a full recovery window with no outcome
    assert b.allow()        # re-issued instead of wedging shut forever
    assert b.state == HALF_OPEN


def test_circuit_state_metric_published():
    clk = _Clock()
    b = CircuitBreaker(failure_threshold=1, name="t5", clock=clk)
    b.on_failure()
    snap = REGISTRY.snapshot(run_collectors=False)
    assert snap['dl4j_circuit_state{breaker="t5"}'] == 2
    assert b.status()["state"] == OPEN


# ---------------------------------------------------------------------------
# serving engine: failure isolation, breaker wiring, launch watchdog
# ---------------------------------------------------------------------------

class _StubModel:
    """Host-only model: fast deterministic forwards, failure on demand."""

    def __init__(self):
        self.fail = None

    def output(self, x):
        if self.fail is not None:
            raise self.fail
        return np.asarray(x, np.float32) * 2.0


def _stub_engine(breaker=None, retry=None, **cfg):
    cfg.setdefault("max_batch", 8)
    cfg.setdefault("settle_ms", 0.0)
    cfg.setdefault("max_delay_ms", 2.0)
    return InferenceEngine(_StubModel(), BatchingConfig(**cfg),
                           graph_opt=False, breaker=breaker, retry=retry)


def _await(req, timeout=10.0):
    assert req.event.wait(timeout), "request hung (future never resolved)"
    return req


def test_model_failure_fails_batch_only_and_dispatcher_survives():
    """Satellite regression: an exception from the model mid-batch must
    fail ONLY that batch's futures (each waiter gets the error) and the
    dispatcher thread must survive to serve the next group."""
    eng = _stub_engine(settle_ms=1.0, max_delay_ms=20.0)
    try:
        eng.model.fail = RuntimeError("bad weights")
        xs = [np.full((n, 4), n, np.float32) for n in (1, 2, 3)]
        reqs = [eng.submit((x,)) for x in xs]
        for r in reqs:
            _await(r)
            with pytest.raises(RuntimeError, match="bad weights"):
                eng.result(r)
        # the dispatcher survived: the very next group is served by the
        # same engine without a restart
        eng.model.fail = None
        out = eng.predict(xs[1])
        np.testing.assert_array_equal(out[:, :4], xs[1] * 2.0)
        assert eng._thread is not None and eng._thread.is_alive()
    finally:
        eng.close()


def test_injected_launch_failures_trip_breaker_then_half_open_recovers():
    """Acceptance invariant: injected serving-launch failures trip the
    breaker open (shedding, not queueing), then recover through
    half-open probes — no dispatcher deadlock, every future resolved."""
    br = CircuitBreaker(failure_threshold=2, recovery_timeout_s=0.25,
                        name="chaos-serving")
    eng = _stub_engine(breaker=br)
    try:
        plan = FaultPlan(seed=3).inject("serving.launch", max_fires=2)
        with plan.armed():
            for _ in range(2):
                req = _await(eng.submit((np.ones((2, 4), np.float32),)))
                with pytest.raises(InjectedFault):
                    eng.result(req)
        assert br.state == OPEN
        # open = fail-fast shedding: the submit itself is rejected
        with pytest.raises(CircuitOpenError):
            eng.submit((np.ones((1, 4), np.float32),))
        time.sleep(0.3)  # recovery timeout elapses -> half-open probe
        out = eng.predict(np.ones((1, 4), np.float32))
        np.testing.assert_array_equal(out, np.full((1, 4), 2.0))
        assert br.state == CLOSED
        assert eng._thread is not None and eng._thread.is_alive()
    finally:
        eng.close()


def test_watchdog_fails_stuck_launch_and_replaces_dispatcher():
    eng = _stub_engine(launch_timeout_ms=80.0)
    try:
        # one stuck launch: the injected delay holds the dispatcher well
        # past launch_timeout_ms
        plan = FaultPlan().inject("serving.launch", action="delay",
                                  delay_s=0.5, max_fires=1)
        with plan.armed():
            req = eng.submit((np.ones((2, 4), np.float32),))
            _await(req, timeout=5.0)
            t_failed = time.monotonic()
            with pytest.raises(LaunchTimeoutError):
                eng.result(req)
            # the waiter was failed by the WATCHDOG, not by the launch
            # finally finishing (which takes 0.5s)
            assert plan.fired("serving.launch") == 1
            # the replacement dispatcher serves the next request even
            # while the stuck thread is still sleeping
            out = eng.predict(np.ones((1, 4), np.float32))
            assert time.monotonic() - t_failed < 0.45
            np.testing.assert_array_equal(out, np.full((1, 4), 2.0))
    finally:
        time.sleep(0.3)  # let the abandoned launch drain before close
        eng.close()


def test_overload_rejection_does_not_burn_half_open_probe():
    """Regression: a submit rejected for overload (or any pre-queue
    reason) must not consume a half-open probe ticket — a burned ticket
    with no outcome would wedge the breaker half-open for a full extra
    recovery window."""
    from deeplearning4j_tpu.parallel.batcher import ServerOverloadedError

    clk = _Clock()
    br = CircuitBreaker(failure_threshold=1, recovery_timeout_s=5.0,
                        name="probe-guard", clock=clk)
    eng = _stub_engine(breaker=br, max_queue=0)  # every submit overloads
    try:
        br.on_failure()     # open at t=0
        clk.t = 5.0         # recovery elapsed: next allow() half-opens
        with pytest.raises(ServerOverloadedError):
            eng.submit((np.ones((1, 4), np.float32),))
        # the one probe ticket is still available: the rejection above
        # never reached the breaker
        assert br.allow()
        assert not br.allow()
    finally:
        eng.close()


def test_train_step_site_fires_on_tbptt_path():
    """Regression: the `train.step` hook must fire once per optimization
    step on the tBPTT branch too, or chaos plans against recurrent
    models silently test nothing."""
    from deeplearning4j_tpu.conf import WeightInit
    from deeplearning4j_tpu.conf.layers_rnn import LSTM, RnnOutputLayer
    from deeplearning4j_tpu.conf.multilayer import BackpropType

    conf = (NeuralNetConfiguration.builder().seed(5)
            .updater(Adam(0.01)).weight_init(WeightInit.XAVIER)
            .list()
            .layer(LSTM(n_out=8))
            .layer(RnnOutputLayer(n_out=3, activation=Activation.SOFTMAX,
                                  loss_fn=LossMCXENT()))
            .backprop_type(BackpropType.TRUNCATED_BPTT, fwd=5, back=5)
            .set_input_type(InputType.recurrent(4, 10)).build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    x = rng.normal(size=(4, 10, 4)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, (4, 10))]
    plan = FaultPlan().inject("train.step", on_calls=[2])
    with plan.armed():
        net.fit(x, y)                       # step 1: passes through
        with pytest.raises(InjectedFault):
            net.fit(x, y)                   # step 2: the armed kill
    assert plan.invocations("train.step") == 2
    assert plan.fired("train.step") == 1


def test_engine_stats_and_resilience_status_surface_breaker():
    br = CircuitBreaker(failure_threshold=1, name="surface-test")
    eng = _stub_engine(breaker=br)
    try:
        br.on_failure()
        st = eng.stats()["circuit_breaker"]
        assert st["name"] == "surface-test" and st["state"] == OPEN
        s = status()
        assert s["circuit_breakers"]["surface-test"]["state"] == OPEN
        assert s["fault_plan_armed"] is False
        with FaultPlan().armed():
            assert status()["fault_plan_armed"] is True
    finally:
        eng.close()


# ---------------------------------------------------------------------------
# TrainingSession: preemption-safe, bit-identical resume
# ---------------------------------------------------------------------------

def _flat(net):
    return np.asarray(net.params_flat())


def _opt_flat(net):
    return np.asarray(params_util.flatten_state_like(net.opt_state))


def _iterator(seed=0):
    return ListDataSetIterator(
        [DataSet(x, y) for x, y in _batches(seed=seed)])


def _baseline_params(epochs=2):
    net = _ckpt_net()
    net.fit(_iterator(), epochs=epochs)
    return _flat(net), _opt_flat(net)


@pytest.mark.parametrize("kill_at", [1, 5])
def test_killed_training_resumes_bit_identical(tmp_path, kill_at):
    """THE acceptance invariant: a fault kills training mid-run; the
    session auto-resumes from its last snapshot and the final params
    (and updater state) are bit-identical to an uninterrupted run.
    ``kill_at=1`` dies before any periodic snapshot (the pre-first-step
    snapshot carries it); ``kill_at=5`` dies between periodic snapshots
    and replays from iteration 4."""
    ref_params, ref_opt = _baseline_params()

    sess = TrainingSession(_ckpt_net(), str(tmp_path),
                           snapshot_every_n_iterations=2)
    before = counter_value("dl4j_resumes_total", scope="job")
    plan = FaultPlan(seed=1).inject("train.step", on_calls=[kill_at])
    with plan.armed():
        sess.fit(_iterator(), epochs=2)
    assert plan.fired("train.step") == 1    # the kill was real
    assert counter_value("dl4j_resumes_total", scope="job") - before == 1
    assert sess.model.epoch == 2
    np.testing.assert_array_equal(_flat(sess.model), ref_params)
    np.testing.assert_array_equal(_opt_flat(sess.model), ref_opt)


def test_resume_after_process_death_from_directory_alone(tmp_path):
    """Process-crash shape: the first session dies (max_restarts=0 -> the
    fault propagates, 'the process is gone'); a brand-new session built
    from the directory alone resumes and finishes bit-identical."""
    ref_params, ref_opt = _baseline_params()

    sess = TrainingSession(_ckpt_net(), str(tmp_path),
                           snapshot_every_n_iterations=2, max_restarts=0)
    plan = FaultPlan().inject("train.step", on_calls=[3])
    with plan.armed():
        with pytest.raises(InjectedFault):
            sess.fit(_iterator(), epochs=2)

    revived = TrainingSession(None, str(tmp_path),
                              snapshot_every_n_iterations=2)
    model = revived.resume()
    assert model.iteration == 2  # the iter-2 snapshot, not a fresh net
    revived.fit(_iterator(), epochs=2)
    assert revived.model.epoch == 2
    np.testing.assert_array_equal(_flat(revived.model), ref_params)
    np.testing.assert_array_equal(_opt_flat(revived.model), ref_opt)


def test_snapshot_retention_keeps_last_and_digests(tmp_path):
    sess = TrainingSession(_ckpt_net(), str(tmp_path),
                           snapshot_every_n_iterations=1, keep_last=2)
    sess.fit(_iterator(), epochs=1)  # 6 steps -> 6+ snapshots written
    snaps = sess.snapshots()
    assert len(snaps) == 2           # retention pruned the rest
    zips = glob.glob(os.path.join(str(tmp_path), "session_iter*.zip"))
    assert len(zips) == 2
    for s in snaps:
        assert s["digest"]           # every row digest-verified on resume
    assert _no_tmp_files(str(tmp_path))


def test_resume_skips_corrupt_newest_snapshot(tmp_path):
    sess = TrainingSession(_ckpt_net(), str(tmp_path),
                           snapshot_every_n_iterations=2, keep_last=3)
    sess.fit(_iterator(), epochs=1)
    snaps = sess.snapshots()
    assert len(snaps) >= 2
    newest = os.path.join(str(tmp_path), snaps[-1]["file"])
    with open(newest, "r+b") as f:
        f.truncate(os.path.getsize(newest) // 3)
    revived = TrainingSession(None, str(tmp_path))
    model = revived.resume()
    # fell back to the previous generation, not the truncated newest
    assert model.iteration == snaps[-2]["iteration"]


def test_to_epoch_resumes_to_original_budget_not_past_it(tmp_path):
    """Regression: a cross-process restart that re-runs the SAME script
    must finish the original epoch budget, not add to it. The run dies
    in epoch 1 of 2; `fit(epochs=2)` after resume would train to epoch 3
    — the absolute `to_epoch=2` form lands bit-identical instead."""
    ref_params, ref_opt = _baseline_params()

    sess = TrainingSession(_ckpt_net(), str(tmp_path),
                           snapshot_every_n_iterations=3, max_restarts=0)
    plan = FaultPlan().inject("train.step", on_calls=[10])  # epoch 1
    with plan.armed():
        with pytest.raises(InjectedFault):
            sess.fit(_iterator(), epochs=2)

    revived = TrainingSession(None, str(tmp_path),
                              snapshot_every_n_iterations=3)
    model = revived.resume()
    assert model.epoch == 1              # died mid second epoch
    revived.fit(_iterator(), to_epoch=2)
    assert revived.model.epoch == 2      # NOT 1 + 2 = 3
    np.testing.assert_array_equal(_flat(revived.model), ref_params)
    np.testing.assert_array_equal(_opt_flat(revived.model), ref_opt)


def test_max_restarts_bounds_a_deterministic_fault(tmp_path):
    # a fault that re-fires on every replay must not loop forever
    sess = TrainingSession(_ckpt_net(), str(tmp_path),
                           snapshot_every_n_iterations=2, max_restarts=2)
    plan = FaultPlan().inject("train.step", probability=1.0)
    with plan.armed():
        with pytest.raises(InjectedFault):
            sess.fit(_iterator(), epochs=1)
    assert sess.restarts == 2


# ---------------------------------------------------------------------------
# ingest + stats-flush edges
# ---------------------------------------------------------------------------

def test_device_ring_retries_transient_device_put():
    from deeplearning4j_tpu.datasets.prefetch import DeviceRingIterator

    batches = [DataSet(x, y) for x, y in _batches(seed=9, n_batches=3)]
    want = [np.asarray(b.features, np.float32) for b in batches]
    ring = DeviceRingIterator(ListDataSetIterator(batches), depth=2)
    before = counter_value("dl4j_retries_total", op="ingest.device_put")
    plan = FaultPlan().inject("ingest.device_put", on_calls=[1])
    with plan.armed():
        staged = [np.asarray(ds.features) for ds in ring]
    assert plan.fired("ingest.device_put") == 1
    assert counter_value("dl4j_retries_total",
                         op="ingest.device_put") - before == 1
    assert len(staged) == 3
    for got, exp in zip(staged, want):
        np.testing.assert_array_equal(got, exp)


def test_stats_flush_retries_then_drops_and_worker_survives():
    from deeplearning4j_tpu.ui.stats import RemoteUIStatsStorageRouter

    router = RemoteUIStatsStorageRouter("http://127.0.0.1:9", retries=2)
    router._retry = RetryPolicy(max_attempts=2, base_delay_s=0.001,
                                retryable=(Exception,), name="stats.flush")
    plan = FaultPlan().inject("stats.flush")  # every delivery attempt
    with plan.armed():
        router.put({"kind": "chaos"})
        router._q.join()
    assert plan.fired("stats.flush") == 2     # initial try + 1 retry
    assert router.dropped == 1
    assert router._thread.is_alive()          # drop, not die


def test_stats_router_retries_zero_still_constructs_and_drops():
    # regression: retries=0 was the historical drop-without-attempting
    # configuration and must not raise at construction
    from deeplearning4j_tpu.ui.stats import RemoteUIStatsStorageRouter

    router = RemoteUIStatsStorageRouter("http://127.0.0.1:9", retries=0)
    plan = FaultPlan().inject("stats.flush")
    with plan.armed():
        router.put({"kind": "chaos"})
        router._q.join()
    assert plan.fired("stats.flush") == 0     # never even attempted
    assert router.dropped == 1
    assert router._thread.is_alive()


# ---------------------------------------------------------------------------
# healthy-path invariants
# ---------------------------------------------------------------------------

def test_disarmed_sites_leave_training_untouched(tmp_path):
    """The permanent hooks are inert when no plan is armed: training
    through the instrumented paths injects nothing and counts nothing."""
    net = _ckpt_net()
    net.fit(_iterator(), epochs=1)
    snap = REGISTRY.snapshot(run_collectors=False)
    assert not any(k.startswith("dl4j_faults_injected_total")
                   for k in snap)
    assert np.isfinite(_flat(net)).all()
