"""The driver's multi-chip gate must survive a jax-pre-initialized caller.

Round-1 regression: ``dryrun_multichip`` relied on an in-process backend
swap (``_force_cpu``) which silently no-ops once any backend is
initialized — the driver's harness touches ``jax.devices()`` first, so
the recorded gate failed (``MULTICHIP_r01.json`` rc=1) even though the
mesh logic passed in a fresh interpreter. The fix re-execs the body in a
scrubbed subprocess; these tests pin that contract.
"""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_dryrun_multichip_survives_preinitialized_jax():
    # Simulate the driver: initialize jax (whatever platform this test
    # env pins — conftest forces cpu with 8 virtual devices, the driver
    # initializes axon; either way the backend is locked) BEFORE calling
    # the gate. The subprocess re-exec must make it pass regardless.
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # child must set its own device count
    proc = subprocess.run(
        [sys.executable, "-c",
         "import jax; jax.devices(); "
         "import __graft_entry__; __graft_entry__.dryrun_multichip(4)"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=1200)
    assert proc.returncode == 0, proc.stderr[-4000:]
    assert "dryrun_multichip(4): OK" in proc.stdout


def test_dryrun_scrubs_axon_env():
    # The child env must not contain the sitecustomize trigger vars even
    # when the parent sets them.
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    # Set the trigger var INSIDE the probe (after interpreter boot) so the
    # probe's own sitecustomize doesn't dial the real axon plugin.
    probe = (
        "import os, __graft_entry__, subprocess\n"
        "os.environ['PALLAS_AXON_POOL_IPS'] = '198.51.100.1'\n"
        "real_run = subprocess.run\n"
        "def spy(cmd, **kw):\n"
        "    e = kw['env']\n"
        "    assert 'PALLAS_AXON_POOL_IPS' not in e\n"
        "    assert e['JAX_PLATFORMS'] == 'cpu'\n"
        "    assert '--xla_force_host_platform_device_count=2' in e['XLA_FLAGS']\n"
        "    class R: returncode, stdout, stderr = 0, 'dryrun ok', ''\n"
        "    return R()\n"
        "subprocess.run = spy\n"
        "__graft_entry__.dryrun_multichip(2)\n"
        "print('SCRUB OK')\n"
    )
    proc = subprocess.run([sys.executable, "-c", probe], cwd=REPO, env=env,
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "SCRUB OK" in proc.stdout
