"""Multi-process CPU pod harness: spawn N coordinator-connected
``jax.distributed`` processes over loopback and run a script body in
each — the test-side stand-in for an N-host pod, with the same
capability-probe-and-skip discipline PR 7 established for
``test_two_process_distributed`` (jaxlibs without cross-process CPU
collectives fail the probe with "Multiprocess computations aren't
implemented on the CPU backend"; those containers SKIP the pod tests
cleanly instead of failing them).

Usage::

    from tests import pod_harness

    def test_something_multi_host(tmp_path):
        pod_harness.require_multiprocess(n=2)   # pytest.skip if absent
        outs = pod_harness.run_pod(BODY, n=2, outdir=str(tmp_path))
        # BODY ran with jax.distributed initialized in every process;
        # sys.argv = [script, process_id, coordinator_port, outdir]

Every worker gets the standard CPU pinning preamble (JAX_PLATFORMS=cpu,
axon backend deregistered, forced host device count) before
``jax.distributed.initialize``; the repo root is on ``sys.path`` so
bodies import ``deeplearning4j_tpu`` and ``tests.*`` helpers directly.
"""

from __future__ import annotations

import functools
import os
import socket
import subprocess
import sys
import textwrap

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_PREAMBLE = textwrap.dedent("""
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ["XLA_FLAGS"] = \\
        "--xla_force_host_platform_device_count={local_devices}"
    import jax
    jax.config.update("jax_platforms", "cpu")
    try:
        from jax._src import xla_bridge as _xb
        _xb._backend_factories.pop("axon", None)
    except Exception:
        pass
    pid = int(sys.argv[1]); port = sys.argv[2]; outdir = sys.argv[3]
    sys.path.insert(0, {repo!r})
    jax.distributed.initialize(coordinator_address="127.0.0.1:" + port,
                               num_processes={n}, process_id=pid)
""")

_PROBE_BODY = textwrap.dedent("""
    import numpy as np
    from jax.experimental import multihost_utils
    multihost_utils.broadcast_one_to_all(np.ones(1, np.float32))
    print("PROBE_OK")
""")


def free_port() -> str:
    """Ephemeral coordinator port (a collision would read as
    'multi-process unsupported')."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return str(s.getsockname()[1])


def _worker_env() -> dict:
    env = {k: v for k, v in os.environ.items()
           if k not in ("PALLAS_AXON_POOL_IPS",)}
    env["JAX_PLATFORMS"] = "cpu"
    return env


def run_pod(body: str, n: int = 2, local_devices: int = 2,
            outdir: str = ".", timeout: float = 300.0,
            check: bool = True):
    """Run ``_PREAMBLE + body`` in ``n`` loopback-coordinated CPU
    processes. Returns a list of per-process ``(returncode, output)``
    pairs; ``check=True`` additionally asserts every process exited 0
    (embedding its tail of output in the failure)."""
    script = _PREAMBLE.format(repo=REPO_ROOT, n=n,
                              local_devices=local_devices) \
        + textwrap.dedent(body)
    port = free_port()
    procs = [subprocess.Popen(
        [sys.executable, "-c", script, str(i), port, str(outdir)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        env=_worker_env()) for i in range(n)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out.decode())
    except Exception:
        for p in procs:
            p.kill()
        raise
    results = [(p.returncode, o) for p, o in zip(procs, outs)]
    if check:
        for i, (rc, out) in enumerate(results):
            assert rc == 0, \
                f"pod worker {i}/{n} failed:\n{out[-3000:]}"
    return results


@functools.lru_cache(maxsize=None)
def cpu_multiprocess_supported(n: int = 2) -> bool:
    """Capability probe: can THIS jax/jaxlib run ``n``-process
    computations on the CPU backend? Feature-probed with ``n`` real
    loopback processes running the same ``broadcast_one_to_all`` the
    distributed paths need."""
    try:
        results = run_pod(_PROBE_BODY, n=n, local_devices=2,
                          timeout=120, check=False)
    except Exception:
        return False
    # exit code AND marker: a worker that prints PROBE_OK then crashes
    # in distributed shutdown must still read as UNSUPPORTED (skip,
    # not flaky-fail — the discipline the old test_cluster probe had)
    return all(rc == 0 and "PROBE_OK" in o for rc, o in results)


def require_multiprocess(n: int = 2) -> None:
    """``pytest.skip`` unless the container can run ``n``-process CPU
    collectives (the probe-and-skip discipline: pod paths run where CI
    supports them, skip cleanly where it doesn't)."""
    import pytest

    if not cpu_multiprocess_supported(n):
        pytest.skip(f"this jax/jaxlib cannot run {n}-process "
                    f"computations on the CPU backend (loopback "
                    f"collective probe failed)")
