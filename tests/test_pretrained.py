"""Zoo pretrained-weights machinery (``ZooModel#initPretrained`` parity).

Zero-egress protocol: the cache is populated via ``save_pretrained`` (the
local publish half) and ``init_pretrained`` resolves/verifies/loads from
it — the same artifact + checksum flow the reference drives through its
weight-download CDN, minus the network leg.
"""

import numpy as np
import pytest

from deeplearning4j_tpu.zoo import (
    LeNet,
    ResNet50,
    PretrainedType,
    restore_partial,
    save_pretrained,
)
from deeplearning4j_tpu.zoo import pretrained as zp
from deeplearning4j_tpu.datasets.dataset import DataSet


@pytest.fixture(autouse=True)
def _cache_home(tmp_path, monkeypatch):
    monkeypatch.setenv("DL4J_TPU_HOME", str(tmp_path))
    yield tmp_path


def test_init_pretrained_round_trip_lenet():
    model = LeNet(num_classes=10, height=8, width=8)
    net = model.init()
    rng = np.random.default_rng(0)
    ds = DataSet(rng.normal(size=(4, 8, 8, 1)).astype(np.float32),
                 np.eye(10, dtype=np.float32)[rng.integers(0, 10, 4)])
    net.fit_batch(ds)  # non-trivial weights
    save_pretrained(net, model.model_name, PretrainedType.MNIST)

    assert model.pretrained_available(PretrainedType.MNIST)
    assert not model.pretrained_available(PretrainedType.VGGFACE)
    restored = model.init_pretrained(PretrainedType.MNIST)
    np.testing.assert_allclose(restored.params_flat(), net.params_flat())
    # loaded model is usable directly
    out = restored.output(ds.features)
    assert out.shape == (4, 10)


def test_init_pretrained_round_trip_resnet50_graph():
    model = ResNet50(num_classes=5, height=32, width=32)
    net = model.init()
    save_pretrained(net, model.model_name, PretrainedType.IMAGENET)
    restored = model.init_pretrained(PretrainedType.IMAGENET)
    np.testing.assert_allclose(restored.params_flat(), net.params_flat())


def test_checksum_corruption_detected(tmp_path):
    model = LeNet(num_classes=10, height=8, width=8)
    save_pretrained(model.init(), model.model_name, PretrainedType.MNIST)
    path = zp.artifact_path(model.model_name, PretrainedType.MNIST)
    data = bytearray(path.read_bytes())
    data[len(data) // 2] ^= 0xFF
    path.write_bytes(bytes(data))
    with pytest.raises(IOError, match="checksum mismatch"):
        model.init_pretrained(PretrainedType.MNIST)


def test_pinned_class_checksum_enforced():
    model = LeNet(num_classes=10, height=8, width=8)
    save_pretrained(model.init(), model.model_name, PretrainedType.MNIST)
    model.PRETRAINED_CHECKSUMS = {PretrainedType.MNIST: "0" * 64}
    with pytest.raises(IOError, match="pins"):
        model.init_pretrained(PretrainedType.MNIST)


def test_unavailable_type_raises():
    model = LeNet(num_classes=10, height=8, width=8)
    with pytest.raises(ValueError, match="no pretrained weights"):
        model.init_pretrained(PretrainedType.VGGFACE)


def test_restore_partial_feeds_transfer_learning():
    """The flagship workflow: pretrained backbone, new head, fine-tune."""
    donor_model = LeNet(num_classes=10, height=8, width=8)
    donor = donor_model.init()
    path = save_pretrained(donor, donor_model.model_name,
                           PretrainedType.MNIST)

    target = LeNet(num_classes=3, height=8, width=8).init()  # new head
    loaded, skipped = restore_partial(path, target)
    # backbone convs + dense load; the 10-class output layer (index 6,
    # after the auto-inserted CNN->FF preprocessor at 4) is skipped
    assert any(k.startswith("0/") for k in loaded)
    assert skipped == ["6/W", "6/b"]
    np.testing.assert_allclose(
        np.asarray(target.params["0"]["W"]),
        np.asarray(donor.params["0"]["W"]))

    from deeplearning4j_tpu.nn.transferlearning import TransferLearning

    tuned = (TransferLearning.Builder(target)
             .set_feature_extractor(5)  # freeze through the dense layer
             .build())
    rng = np.random.default_rng(0)
    ds = DataSet(rng.normal(size=(4, 8, 8, 1)).astype(np.float32),
                 np.eye(3, dtype=np.float32)[rng.integers(0, 3, 4)])
    before = np.asarray(tuned.params["0"]["W"]).copy()
    tuned.fit_batch(ds)
    # frozen backbone untouched, head trains
    np.testing.assert_allclose(np.asarray(tuned.params["0"]["W"]), before)


def test_missing_cache_and_url_message():
    model = LeNet(num_classes=10, height=8, width=8)
    model.PRETRAINED_URLS = {PretrainedType.MNIST: ""}  # available, no URL
    with pytest.raises(FileNotFoundError, match="save_pretrained"):
        model.init_pretrained(PretrainedType.MNIST)
