"""SameDiff-equivalent tests.

Mirrors the reference's nd4j-tests op validation + SameDiff gradient checks
(SURVEY.md §4 "Op-level validation"): forward values vs numpy, gradients vs
central differences, training convergence, serde round-trip, and the
BASELINE config #3 models (LSTM + small Transformer).
"""

import numpy as np
import pytest

from deeplearning4j_tpu.conf.updaters import Adam, Sgd
from deeplearning4j_tpu.samediff import (SameDiff, TrainingConfig,
                                         VariableType)


def test_basic_arithmetic_and_eval(rng):
    sd = SameDiff.create()
    a = sd.var("a", value=rng.normal(size=(3, 4)).astype(np.float32))
    b = sd.var("b", value=rng.normal(size=(3, 4)).astype(np.float32))
    c = (a + b) * 2.0 - a / (sd.math.abs(b) + 1.0)
    out = c.eval()
    av, bv = np.asarray(a.get_arr()), np.asarray(b.get_arr())
    expect = (av + bv) * 2.0 - av / (np.abs(bv) + 1.0)
    np.testing.assert_allclose(out, expect, rtol=1e-5)


def test_placeholder_and_matmul(rng):
    sd = SameDiff.create()
    x = sd.placeholder("x", shape=(None, 4))
    w = sd.var("w", value=rng.normal(size=(4, 3)).astype(np.float32))
    y = sd.math.mmul(x, w)
    xv = rng.normal(size=(5, 4)).astype(np.float32)
    out = sd.output({"x": xv}, y)[y.name]
    np.testing.assert_allclose(out, xv @ np.asarray(w.get_arr()), rtol=1e-4)


def test_reductions_and_argmax(rng):
    sd = SameDiff.create()
    xv = rng.normal(size=(4, 6)).astype(np.float32)
    x = sd.constant(xv, name="x")
    s = sd.math.sum(x, dims=1)
    m = sd.math.mean(x)
    am = sd.math.argmax(x, dim=1)
    outs = sd.output({}, s, m, am)
    np.testing.assert_allclose(outs[s.name], xv.sum(1), rtol=1e-5)
    np.testing.assert_allclose(outs[m.name], xv.mean(), rtol=1e-5)
    np.testing.assert_array_equal(outs[am.name], xv.argmax(1))


def test_variable_types_and_rename(rng):
    sd = SameDiff.create()
    v = sd.var("w", shape=(2, 2))
    c = sd.constant(np.eye(2, dtype=np.float32), name="c")
    p = sd.placeholder("x", shape=(2, 2))
    assert v.var_type == VariableType.VARIABLE
    assert c.var_type == VariableType.CONSTANT
    assert p.var_type == VariableType.PLACEHOLDER
    y = v + c
    assert y.var_type == VariableType.ARRAY
    y.rename("sum_out")
    out = sd.output({"x": np.zeros((2, 2), np.float32)}, "sum_out")
    assert out["sum_out"].shape == (2, 2)


def test_calculate_gradients_vs_numeric(rng):
    sd = SameDiff.create()
    w = sd.var("w", value=rng.normal(size=(3, 2)).astype(np.float64))
    x = sd.constant(rng.normal(size=(4, 3)).astype(np.float64), name="x")
    y = sd.math.mmul(x, w)
    loss = sd.math.sum(sd.math.square(sd.math.tanh(y)))
    sd.set_loss_variables(loss)
    grads = sd.calculate_gradients({}, "w")

    wv = np.asarray(w.get_arr(), dtype=np.float64)
    xv = np.asarray(x.get_arr(), dtype=np.float64)

    def f(wm):
        return np.sum(np.tanh(xv @ wm) ** 2)

    eps = 1e-5
    num = np.zeros_like(wv)
    for i in range(wv.shape[0]):
        for j in range(wv.shape[1]):
            wp, wm_ = wv.copy(), wv.copy()
            wp[i, j] += eps
            wm_[i, j] -= eps
            num[i, j] = (f(wp) - f(wm_)) / (2 * eps)
    np.testing.assert_allclose(np.asarray(grads["w"]), num, rtol=1e-3,
                               atol=1e-5)


def test_fit_linear_regression(rng):
    sd = SameDiff.create()
    x = sd.placeholder("x", shape=(None, 3))
    labels = sd.placeholder("labels", shape=(None, 1))
    w = sd.var("w", value=np.zeros((3, 1), np.float32))
    b = sd.var("b", value=np.zeros((1,), np.float32))
    pred = sd.math.mmul(x, w) + b
    sd.loss.meanSquaredError(labels, pred, name="loss")

    true_w = np.array([[1.5], [-2.0], [0.5]], np.float32)
    xv = rng.normal(size=(256, 3)).astype(np.float32)
    yv = xv @ true_w + 0.3

    cfg = (TrainingConfig.builder()
           .updater(Adam(learning_rate=0.1))
           .data_set_feature_mapping("x")
           .data_set_label_mapping("labels")
           .build())
    sd.set_training_config(cfg)
    hist = None
    for _ in range(60):
        hist = sd.fit(features=xv, labels=yv)
    assert hist.loss_curve[-1] < 1e-2
    np.testing.assert_allclose(np.asarray(w.get_arr()), true_w, atol=0.05)
    np.testing.assert_allclose(np.asarray(b.get_arr()), [0.3], atol=0.05)


def test_mlp_classification_convergence(rng):
    sd = SameDiff.create()
    x = sd.placeholder("x", shape=(None, 2))
    labels = sd.placeholder("labels", shape=(None, 2))
    w0 = sd.var("w0", shape=(2, 16), key=None)
    b0 = sd.var("b0", value=np.zeros((16,), np.float32))
    w1 = sd.var("w1", shape=(16, 2))
    b1 = sd.var("b1", value=np.zeros((2,), np.float32))
    h = sd.nn.relu(sd.nn.linear(x, w0, b0))
    logits = sd.nn.linear(h, w1, b1)
    sd.loss.softmaxCrossEntropy(labels, logits, name="loss")

    n = 256
    xv = rng.normal(size=(n, 2)).astype(np.float32)
    cls = (xv[:, 0] * xv[:, 1] > 0).astype(int)  # XOR-ish quadrant task
    yv = np.eye(2, dtype=np.float32)[cls]

    sd.set_training_config(TrainingConfig.builder()
                           .updater(Adam(learning_rate=0.05))
                           .data_set_feature_mapping("x")
                           .data_set_label_mapping("labels")
                           .build())
    for _ in range(150):
        sd.fit(features=xv, labels=yv)
    probs = sd.output({"x": xv}, logits)[logits.name]
    acc = (probs.argmax(1) == cls).mean()
    assert acc > 0.9


def test_control_flow_cond_and_while():
    sd = SameDiff.create()
    x = sd.constant(np.float32(3.0), name="x")
    pred = sd.math.gt(x, 0.0)
    out = sd.cond(pred, lambda v: v * 2.0, lambda v: v - 1.0, [x])
    assert float(out.eval()) == 6.0

    sd2 = SameDiff.create()
    i = sd2.constant(np.float32(0.0), name="i")
    acc = sd2.constant(np.float32(1.0), name="acc")
    outs = sd2.while_loop(
        lambda i_, a_: i_ < 5.0,
        lambda i_, a_: (i_ + 1.0, a_ * 2.0),
        [i, acc])
    vals = sd2.output({}, *outs)
    assert float(vals[outs[1].name]) == 32.0


def test_scan_cumulative():
    sd = SameDiff.create()
    xs = sd.constant(np.arange(1, 6, dtype=np.float32), name="xs")
    init = sd.constant(np.float32(0.0), name="init")

    def body(carry, xt):
        s = carry + xt
        return s, s

    final, ys = sd.scan(body, init, xs)
    outs = sd.output({}, final, ys)
    assert float(outs[final.name]) == 15.0
    np.testing.assert_allclose(outs[ys.name], np.cumsum(np.arange(1, 6)))


def test_lstm_layer_shapes_and_grad(rng):
    """BASELINE config #3a: SameDiff LSTM."""
    T, B, I, H = 7, 4, 5, 8
    sd = SameDiff.create()
    x = sd.placeholder("x", shape=(T, B, I))
    w = sd.var("w", value=(0.1 * rng.normal(size=(I, 4 * H))).astype(
        np.float32))
    r = sd.var("r", value=(0.1 * rng.normal(size=(H, 4 * H))).astype(
        np.float32))
    b = sd.var("b", value=np.zeros((4 * H,), np.float32))
    h0 = sd.constant(np.zeros((B, H), np.float32), name="h0")
    c0 = sd.constant(np.zeros((B, H), np.float32), name="c0")
    ys, h_f, c_f = sd.rnn.lstmLayer(x, w, r, b, h0, c0)
    loss = sd.math.sum(sd.math.square(ys))
    sd.set_loss_variables(loss)

    xv = rng.normal(size=(T, B, I)).astype(np.float32)
    outs = sd.output({"x": xv}, ys, h_f, c_f)
    assert outs[ys.name].shape == (T, B, H)
    assert outs[h_f.name].shape == (B, H)
    np.testing.assert_allclose(outs[ys.name][-1], outs[h_f.name], rtol=1e-5)

    grads = sd.calculate_gradients({"x": xv}, "w", "r", "b")
    assert grads["w"].shape == (I, 4 * H)
    assert float(np.abs(np.asarray(grads["w"])).sum()) > 0


def test_small_transformer_block(rng):
    """BASELINE config #3b: small Transformer encoder block via
    multiHeadDotProductAttention + layerNorm + FFN, trained a few steps."""
    B, T, E, HEADS = 4, 6, 16, 4
    sd = SameDiff.create()
    x = sd.placeholder("x", shape=(B, T, E))
    labels = sd.placeholder("labels", shape=(B, E))

    def pvar(name, shape):
        return sd.var(name, value=(0.1 * rng.normal(size=shape)).astype(
            np.float32))

    wq, wk, wv = pvar("wq", (E, E)), pvar("wk", (E, E)), pvar("wv", (E, E))
    wo = pvar("wo", (E, E))
    att = sd.nn.multiHeadDotProductAttention(x, x, x, wq, wk, wv, wo,
                                             num_heads=HEADS)
    g1 = sd.var("g1", value=np.ones((E,), np.float32))
    bt1 = sd.var("bt1", value=np.zeros((E,), np.float32))
    norm1 = sd.nn.layerNorm(att + x, g1, bt1)
    w1, b1 = pvar("w1", (E, 4 * E)), sd.var(
        "b1", value=np.zeros((4 * E,), np.float32))
    w2, b2 = pvar("w2", (4 * E, E)), sd.var(
        "b2", value=np.zeros((E,), np.float32))
    ffn = sd.nn.linear(sd.nn.gelu(sd.nn.linear(norm1, w1, b1)), w2, b2)
    g2 = sd.var("g2", value=np.ones((E,), np.float32))
    bt2 = sd.var("bt2", value=np.zeros((E,), np.float32))
    enc = sd.nn.layerNorm(ffn + norm1, g2, bt2)
    pooled = sd.math.mean(enc, dims=1)
    sd.loss.meanSquaredError(labels, pooled, name="loss")

    xv = rng.normal(size=(B, T, E)).astype(np.float32)
    yv = rng.normal(size=(B, E)).astype(np.float32)
    sd.set_training_config(TrainingConfig.builder()
                           .updater(Adam(learning_rate=0.01))
                           .data_set_feature_mapping("x")
                           .data_set_label_mapping("labels")
                           .build())
    losses = []
    for _ in range(30):
        h = sd.fit(features=xv, labels=yv)
        losses.append(h.loss_curve[-1])
    assert losses[-1] < losses[0] * 0.5


def test_attention_masking(rng):
    B, T, E = 2, 5, 8
    sd = SameDiff.create()
    q = sd.placeholder("q", shape=(B, T, E))
    mask = sd.placeholder("mask", shape=(B, T))
    out = sd.nn.dotProductAttention(q, q, q, mask=mask)
    qv = rng.normal(size=(B, T, E)).astype(np.float32)
    mv = np.ones((B, T), np.float32)
    mv[:, -2:] = 0  # last two kv positions masked out
    o = sd.output({"q": qv, "mask": mv}, out)[out.name]
    # masked result must differ from unmasked and contain no NaN
    o_full = sd.output({"q": qv, "mask": np.ones((B, T), np.float32)},
                       out)[out.name]
    assert np.isfinite(o).all()
    assert np.abs(o - o_full).max() > 1e-6


def test_serde_roundtrip(tmp_path, rng):
    sd = SameDiff.create()
    x = sd.placeholder("x", shape=(None, 4))
    w = sd.var("w", value=rng.normal(size=(4, 3)).astype(np.float32))
    b = sd.var("b", value=np.zeros((3,), np.float32))
    logits = sd.nn.linear(x, w, b).rename("logits")
    labels = sd.placeholder("labels", shape=(None, 3))
    sd.loss.softmaxCrossEntropy(labels, logits, name="loss")

    xv = rng.normal(size=(8, 4)).astype(np.float32)
    yv = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 8)]
    sd.set_training_config(TrainingConfig.builder()
                           .updater(Adam(learning_rate=0.01))
                           .data_set_feature_mapping("x")
                           .data_set_label_mapping("labels")
                           .build())
    sd.fit(features=xv, labels=yv)
    before = sd.output({"x": xv}, "logits")["logits"]

    path = tmp_path / "model.sdz"
    sd.save(str(path))
    sd2 = SameDiff.load(str(path))
    after = sd2.output({"x": xv}, "logits")["logits"]
    np.testing.assert_allclose(before, after, rtol=1e-6)
    # updater state survives -> continued training matches
    sd2.set_training_config(TrainingConfig.builder()
                            .updater(Adam(learning_rate=0.01))
                            .data_set_feature_mapping("x")
                            .data_set_label_mapping("labels")
                            .build())
    sd2.fit(features=xv, labels=yv)


def test_serde_control_flow_roundtrip(tmp_path):
    """cond/while/scan bodies written against SDVariable ops serialize as
    child graphs and rebuild at load (reference: FlatBuffers control-flow
    frames survive SameDiff#save/load)."""
    sd = SameDiff.create()
    x = sd.constant(np.float32(3.0), name="x")
    out = sd.cond(sd.math.gt(x, 0.0), lambda v: v * 2.0,
                  lambda v: v - 1.0, [x])
    path = str(tmp_path / "cf.sdz")
    sd.save(path)
    sd2 = SameDiff.load(path)
    assert float(sd2.output({}, out.name)[out.name]) == 6.0

    sd3 = SameDiff.create()
    i = sd3.constant(np.float32(0.0), name="i")
    acc = sd3.constant(np.float32(1.0), name="acc")
    outs = sd3.while_loop(lambda i_, a_: i_ < 5.0,
                          lambda i_, a_: (i_ + 1.0, a_ * 2.0), [i, acc])
    xs = sd3.constant(np.arange(1, 4, dtype=np.float32), name="xs")
    init = sd3.constant(np.float32(0.0), name="init")
    final, ys = sd3.scan(lambda c, t: (c + t, c + t), init, xs)
    p3 = str(tmp_path / "cf3.sdz")
    sd3.save(p3)
    sd4 = SameDiff.load(p3)
    vals = sd4.output({}, outs[1].name, final.name, ys.name)
    assert float(vals[outs[1].name]) == 32.0
    assert float(vals[final.name]) == 6.0
    np.testing.assert_allclose(vals[ys.name], [1.0, 3.0, 6.0])


def test_serde_rejects_raw_jax_control_flow(tmp_path):
    import jax.numpy as jnp

    sd = SameDiff.create()
    x = sd.constant(np.float32(1.0), name="x")
    # body escapes to raw jax -> still executable, but not serializable
    out = sd.cond(sd.math.gt(x, 0.0), lambda v: jnp.sin(v),
                  lambda v: -v, [x])
    assert float(out.eval()) == pytest.approx(np.sin(1.0))
    with pytest.raises(ValueError, match="not\\s+serializable"):
        sd.save(str(tmp_path / "bad.sdz"))


def test_shape_ops_and_indexing(rng):
    sd = SameDiff.create()
    xv = rng.normal(size=(2, 3, 4)).astype(np.float32)
    x = sd.constant(xv, name="x")
    r = sd.reshape(x, (6, 4))
    p = sd.permute(x, (2, 0, 1))
    sl = x[:, 1, :]
    outs = sd.output({}, r, p, sl)
    np.testing.assert_allclose(outs[r.name], xv.reshape(6, 4))
    np.testing.assert_allclose(outs[p.name], xv.transpose(2, 0, 1))
    np.testing.assert_allclose(outs[sl.name], xv[:, 1, :])


def test_gather_onehot_concat(rng):
    sd = SameDiff.create()
    emb = sd.var("emb", value=rng.normal(size=(10, 4)).astype(np.float32))
    idx = sd.constant(np.array([1, 3, 5], np.int32), name="idx")
    g = sd.gather(emb, idx, axis=0)
    oh = sd.one_hot(idx, 10)
    cat = sd.concat(1, g, g)
    outs = sd.output({}, g, oh, cat)
    np.testing.assert_allclose(outs[g.name],
                               np.asarray(emb.get_arr())[[1, 3, 5]])
    assert outs[oh.name].shape == (3, 10)
    assert outs[cat.name].shape == (3, 8)


def test_losses_match_numpy(rng):
    sd = SameDiff.create()
    logits_v = rng.normal(size=(6, 4)).astype(np.float32)
    labels_v = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 6)]
    logits = sd.constant(logits_v, name="logits")
    labels = sd.constant(labels_v, name="labels")
    ce = sd.loss.softmaxCrossEntropy(labels, logits)
    out = float(ce.eval())
    lp = logits_v - logits_v.max(1, keepdims=True)
    lp = lp - np.log(np.exp(lp).sum(1, keepdims=True))
    expect = float((-labels_v * lp).sum(1).mean())
    assert abs(out - expect) < 1e-5


def test_sgd_minimize_false(rng):
    """minimize=False climbs the objective."""
    sd = SameDiff.create()
    w = sd.var("w", value=np.float32([0.1]))
    obj = sd.math.neg(sd.math.square(w)).rename("obj")  # max at w=0... climb
    sd.set_loss_variables(obj)
    sd.set_training_config(TrainingConfig.builder()
                           .updater(Sgd(learning_rate=0.1))
                           .minimize(False).build())
    for _ in range(5):
        sd.fit(features=np.zeros((1, 1), np.float32),
               labels=np.zeros((1, 1), np.float32))
    assert float(np.abs(np.asarray(w.get_arr())).max()) < 0.1  # toward 0


def test_serde_nested_control_flow(tmp_path):
    import jax.numpy as jnp

    # fully-symbolic nesting round-trips
    sd = SameDiff.create()
    x = sd.constant(np.float32(2.0), name="x")
    out = sd.cond(
        sd.math.gt(x, 0.0),
        lambda v: v.sd.cond(v.sd.math.gt(v, 1.0), lambda u: u * 10.0,
                            lambda u: u, [v]),
        lambda v: v - 1.0, [x])
    p = str(tmp_path / "nested.sdz")
    sd.save(p)
    sd2 = SameDiff.load(p)
    assert float(sd2.output({}, out.name)[out.name]) == 20.0

    # raw-jax INNER body poisons the outer trace -> save rejects, exec works
    sd3 = SameDiff.create()
    y = sd3.constant(np.float32(2.0), name="y")
    o3 = sd3.cond(
        sd3.math.gt(y, 0.0),
        lambda v: v.sd.cond(v.sd.math.gt(v, 1.0), lambda u: jnp.sin(u),
                            lambda u: u, [v]),
        lambda v: v - 1.0, [y])
    assert float(o3.eval()) == pytest.approx(np.sin(2.0))
    with pytest.raises(ValueError, match="not\\s+serializable"):
        sd3.save(str(tmp_path / "bad.sdz"))


def test_cond_multi_output_exec_and_serde(tmp_path):
    sd = SameDiff()
    x = sd.placeholder("x", (3,))
    p = sd.math.gt(sd.math.sum(x), sd.constant(np.float64(0.0)))
    a, b = sd.cond(p,
                   lambda v: (v * 2.0, -v),
                   lambda v: (-v, v * 2.0),
                   [x], n_out=2)
    a.rename("a"); b.rename("b")
    xv = np.asarray([1.0, 2.0, 3.0])
    out = sd.output({"x": xv}, "a", "b")
    np.testing.assert_allclose(np.asarray(out["a"]), xv * 2)
    np.testing.assert_allclose(np.asarray(out["b"]), -xv)
    path = str(tmp_path / "mcond.sdnb")
    sd.save(path)
    sd2 = SameDiff.load(path)
    out2 = sd2.output({"x": -xv}, "a", "b")
    np.testing.assert_allclose(np.asarray(out2["a"]), xv)
    np.testing.assert_allclose(np.asarray(out2["b"]), -xv * 2)


def test_bounded_while_loop_differentiable(tmp_path):
    """while_loop(max_iterations=N) lowers to a masked scan: same results
    as the unbounded form when the loop exits in time, and jax.grad works
    through it (raw lax.while_loop has no transpose rule)."""
    import jax
    import jax.numpy as jnp

    def build(bound):
        sd = SameDiff()
        x = sd.placeholder("x", ())
        i0 = sd.constant(np.float64(0.0), name="i0")
        outs = sd.while_loop(
            lambda i, v: i < 3.0,
            lambda i, v: (i + 1.0, v * 2.0),
            [i0, x], max_iterations=bound)
        outs[1].rename("y")
        return sd

    sd = build(10)
    out = sd.output({"x": np.float64(1.5)}, "y")
    np.testing.assert_allclose(np.asarray(out["y"]), 1.5 * 8)
    # unbounded result agrees
    out_u = build(None).output({"x": np.float64(1.5)}, "y")
    np.testing.assert_allclose(np.asarray(out_u["y"]), 1.5 * 8)
    # gradient: d(8x)/dx = 8 — impossible with the unbounded lowering
    fn = sd.make_function(("y",))
    g = jax.grad(lambda x: jnp.sum(
        fn(dict(sd.arrays), {"x": x})["y"]))(jnp.asarray(1.5))
    np.testing.assert_allclose(np.asarray(g), 8.0)
    # serde round-trips the bound
    path = str(tmp_path / "bw.sdnb")
    sd.save(path)
    sd2 = SameDiff.load(path)
    out2 = sd2.output({"x": np.float64(2.0)}, "y")
    np.testing.assert_allclose(np.asarray(out2["y"]), 16.0)


def test_bounded_while_loop_boundary_safe_gradient():
    """The masked step must NOT evaluate the body past loop exit: a body
    that divides by zero exactly at the exit state would otherwise poison
    gradients with 0*inf NaNs (review finding; lax.cond evaluates only
    the live branch)."""
    import jax
    import jax.numpy as jnp

    sd = SameDiff()
    x = sd.placeholder("x", ())
    i0 = sd.constant(np.float32(0.0), name="i0")
    outs = sd.while_loop(
        lambda i, v: i < 3.0,
        lambda i, v: (i + 1.0, v / (3.0 - i)),  # div-by-zero AT exit i=3
        [i0, x], max_iterations=10)
    outs[1].rename("y")
    fn = sd.make_function(("y",))
    out = fn(dict(sd.arrays), {"x": jnp.asarray(6.0)})["y"]
    np.testing.assert_allclose(np.asarray(out), 1.0)  # 6/(3*2*1)
    g = jax.grad(lambda x: jnp.sum(
        fn(dict(sd.arrays), {"x": x})["y"]))(jnp.asarray(6.0))
    np.testing.assert_allclose(np.asarray(g), 1.0 / 6.0, rtol=1e-6)


def test_bounded_while_loop_body_arity_checked():
    import pytest as _pytest

    sd = SameDiff()
    x = sd.placeholder("x", ())
    i0 = sd.constant(np.float32(0.0), name="i0")
    with _pytest.raises(ValueError, match="carry"):
        outs = sd.while_loop(
            lambda i, v: i < 3.0,
            lambda i, v: (i + 1.0, v * 2.0, v + 1.0),  # 3 outs, 2 carry
            [i0, x], max_iterations=4)
        sd.output({"x": np.float32(1.0)}, outs[1].name)
