"""Attention ops: flash (Pallas, interpret mode on CPU), blockwise, ring.

Oracle = full-materialization reference_attention, per the reference's
gradient-check-everything test strategy (SURVEY.md §4)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import Mesh

from deeplearning4j_tpu.ops.attention import (
    reference_attention, blockwise_attention, flash_attention,
    dot_product_attention)
from deeplearning4j_tpu.ops.ring import ring_attention


def _qkv(rng, b=2, h=3, t=96, d=32):
    q = jnp.asarray(rng.standard_normal((b, h, t, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, h, t, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, h, t, d)), jnp.float32)
    km = jnp.asarray(rng.random((b, t)) > 0.2, jnp.float32)
    # ensure no fully-masked row
    km = km.at[:, 0].set(1.0)
    return q, k, v, km


@pytest.mark.parametrize("causal", [False, True])
def test_blockwise_matches_reference(rng, causal):
    q, k, v, km = _qkv(rng)
    ref = reference_attention(q, k, v, km, causal)
    blk = blockwise_attention(q, k, v, km, causal, block_k=32)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(blk),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_reference(rng, causal):
    q, k, v, km = _qkv(rng)
    ref = reference_attention(q, k, v, km, causal)
    fl = flash_attention(q, k, v, km, causal, block_q=32, block_k=32)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(fl),
                               atol=2e-5, rtol=2e-5)


def test_flash_unpadded_time(rng):
    # T not a multiple of the block size exercises the padding path
    q, k, v, km = _qkv(rng, t=80)
    ref = reference_attention(q, k, v, km, False)
    fl = flash_attention(q, k, v, km, False, block_q=32, block_k=32)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(fl),
                               atol=2e-5, rtol=2e-5)


def test_flash_gradients_match(rng):
    q, k, v, km = _qkv(rng, t=64)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, km, True) ** 2)

    def loss_fl(q, k, v):
        return jnp.sum(
            flash_attention(q, k, v, km, True, block_q=32, block_k=32) ** 2)

    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    gf = jax.grad(loss_fl, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gr, gf):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-4)


def test_blockwise_gradients_match(rng):
    q, k, v, km = _qkv(rng, t=64)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, km, True) ** 2)

    def loss_blk(q, k, v):
        return jnp.sum(
            blockwise_attention(q, k, v, km, True, block_k=16) ** 2)

    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    gb = jax.grad(loss_blk, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gr, gb):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-4)


@pytest.fixture
def seq_mesh():
    devs = np.array(jax.devices()[:8])
    return Mesh(devs, ("sequence",))


@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_reference(rng, seq_mesh, causal):
    q, k, v, km = _qkv(rng, t=64)
    ref = reference_attention(q, k, v, km, causal)
    r = ring_attention(q, k, v, seq_mesh, km, causal=causal)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(r),
                               atol=2e-5, rtol=2e-5)


def test_ring_gradients_match(rng, seq_mesh):
    q, k, v, km = _qkv(rng, t=64)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, km, True) ** 2)

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention(q, k, v, seq_mesh, km, causal=True) ** 2)

    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    gg = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gr, gg):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-4)


def test_dispatcher_runs(rng):
    q, k, v, km = _qkv(rng, t=32)
    out = dot_product_attention(q, k, v, km, causal=True)
    ref = reference_attention(q, k, v, km, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
