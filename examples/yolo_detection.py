"""TinyYOLO: train on synthetic boxes, extract detections with NMS."""
import sys
sys.path.insert(0, str(__import__("pathlib").Path(__file__).resolve().parents[1]))

import numpy as np

from deeplearning4j_tpu.conf.layers_objdetect import (
    Yolo2OutputLayer, get_predicted_objects, nms)
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.zoo.graphs import TinyYOLO

m = TinyYOLO(num_classes=3, height=64, width=64)
net = m.init()
rng = np.random.default_rng(0)
feats = rng.normal(size=(4, 64, 64, 3)).astype(np.float32)
labels = np.zeros((4, 2, 2, 7), np.float32)
labels[:, 0, 1, 0:4] = [1.2, 0.2, 1.8, 0.9]  # grid-unit box
labels[:, 0, 1, 4] = 1.0                     # class 0
ds = DataSet(feats, labels)
for i in range(150):
    loss = net.fit_batch(ds)
print("final yolo loss:", loss)
layer = Yolo2OutputLayer(boxes=m.boxes)
objs = nms(get_predicted_objects(layer, np.asarray(net.output(feats)),
                                 threshold=0.05))
print("detections:", [(o.example, o.predicted_class,
                       round(o.confidence, 2)) for o in objs[:5]])
