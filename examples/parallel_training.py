import sys
sys.path.insert(0, str(__import__("pathlib").Path(__file__).resolve().parents[1]))
import numpy as np, jax
print("backend:", jax.default_backend())
from deeplearning4j_tpu.conf import Activation, InputType
from deeplearning4j_tpu.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.conf.losses import LossMCXENT
from deeplearning4j_tpu.conf.multilayer import NeuralNetConfiguration
from deeplearning4j_tpu.conf.updaters import Sgd
from deeplearning4j_tpu.datasets.iterators import ArrayDataSetIterator
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel import (SparkDl4jMultiLayer,
    SharedTrainingMaster, ParameterAveragingTrainingMaster)

conf = (NeuralNetConfiguration.builder().seed(1).updater(Sgd(0.1)).list()
        .layer(DenseLayer(n_out=8, activation=Activation.TANH))
        .layer(OutputLayer(n_out=3, activation=Activation.SOFTMAX, loss_fn=LossMCXENT()))
        .set_input_type(InputType.feed_forward(4)).build())
rng = np.random.default_rng(0)
x = rng.normal(size=(64, 4)).astype(np.float32)
y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 64)]
for master in (SharedTrainingMaster(), SharedTrainingMaster(threshold=1e-4),
               ParameterAveragingTrainingMaster(averaging_frequency=2)):
    net = MultiLayerNetwork(conf); net.init()
    sn = SparkDl4jMultiLayer(None, net, master)
    it = ArrayDataSetIterator(x, y, batch=32)
    s0 = None
    for _ in range(6):
        sn.fit(it)
        s0 = s0 or sn.score
    print(type(master).__name__, f"{s0:.4f} -> {sn.score:.4f}")
    assert sn.score < s0
print("ALL CLUSTER DRIVE CHECKS PASSED")
