"""Round-2 features tour: bf16 mixed precision, zoo pretrained weights,
transfer learning, and long-context flash attention.

- ``conf.compute_dtype="bfloat16"``: forward/backward run on the MXU in
  bf16 while params/opt-state/BN-stats/loss stay f32 masters (~2x
  ResNet-50 step time on a v5e; see BASELINE.md).
- ``zoo.pretrained``: the reference's ``ZooModel#initPretrained``
  workflow against a local, checksum-verified cache.
- ``ops.flash_attention``: the Pallas kernel that is the only trainable
  attention path at T=16k (BASELINE.md round-2 table).
"""
import sys

sys.path.insert(0, str(__import__("pathlib").Path(__file__).resolve().parents[1]))

import dataclasses

import numpy as np

from deeplearning4j_tpu.conf.updaters import Adam
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.zoo import (
    PretrainedType,
    ResNet50,
    restore_partial,
    save_pretrained,
)

# --- 1. train a (tiny) ResNet-50 under the bf16 compute policy ------------
model = ResNet50(num_classes=10, height=32, width=32,
                 updater=Adam(learning_rate=1e-3))
cfg = dataclasses.replace(model.conf(), compute_dtype="bfloat16")
net = ComputationGraph(cfg).init()

rng = np.random.default_rng(0)
ds = DataSet(rng.integers(0, 256, (16, 32, 32, 3), dtype=np.uint8),
             np.eye(10, dtype=np.float32)[rng.integers(0, 10, 16)])
for i in range(5):
    loss = net.fit_batch(ds)
print(f"bf16-policy training loss: {loss:.4f} "
      "(params stayed f32 masters)")

# --- 2. publish + reload as a pretrained artifact -------------------------
path = save_pretrained(net, model.model_name, PretrainedType.CIFAR10)
print("published:", path)
restored = model.init_pretrained(PretrainedType.CIFAR10)
print("checksum-verified reload OK:",
      np.allclose(restored.params_flat(), net.params_flat()))

# --- 3. transfer: same backbone, new 3-class head -------------------------
target = ResNet50(num_classes=3, height=32, width=32).init()
loaded, skipped = restore_partial(path, target)
print(f"partial load: {len(loaded)} tensors loaded, "
      f"{len(skipped)} head tensors left at init -> fine-tune away")

# --- 4. long-context attention: the flash kernel --------------------------
import jax
import jax.numpy as jnp

from deeplearning4j_tpu.ops import dot_product_attention

B, H, T, D = 1, 4, 4096, 64
mk = lambda: jnp.asarray(  # noqa: E731
    np.random.default_rng(1).normal(size=(B, H, T, D)), jnp.bfloat16)
out = jax.jit(lambda q, k, v: dot_product_attention(
    q, k, v, causal=True))(mk(), mk(), mk())
print(f"T={T} causal attention out: {out.shape} {out.dtype} "
      "(dispatcher picked the Pallas flash kernel on TPU)")
