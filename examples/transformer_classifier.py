import sys
sys.path.insert(0, str(__import__("pathlib").Path(__file__).resolve().parents[1]))
import numpy as np, jax
print("backend:", jax.default_backend())
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.zoo.graphs import TransformerEncoder

rng = np.random.default_rng(0)
net = TransformerEncoder(num_classes=2, embed_dim=64, n_heads=4, n_layers=2,
                         max_len=256).init()
x = rng.normal(size=(16, 256, 64)).astype(np.float32)
y = np.eye(2, dtype=np.float32)[(x[:, :, 0].mean(1) > 0).astype(int)]
ds = DataSet(x, y)
s0 = net.fit_batch(ds)
for _ in range(25):
    s1 = net.fit_batch(ds)
print(f"transformer T=256: {s0:.3f} -> {s1:.3f}")
assert s1 < s0
# flash kernel variant trains too
net2 = TransformerEncoder(num_classes=2, embed_dim=64, n_heads=4, n_layers=1,
                          max_len=256, attention_impl="flash").init()
s0 = net2.fit_batch(ds)
for _ in range(5):
    s1 = net2.fit_batch(ds)
print(f"flash-impl transformer: {s0:.3f} -> {s1:.3f}")
assert s1 < s0
print("TRANSFORMER DRIVE OK")
