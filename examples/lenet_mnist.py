"""LeNet on MNIST: zoo model -> fit -> evaluate -> serializer round-trip."""
import sys
sys.path.insert(0, str(__import__("pathlib").Path(__file__).resolve().parents[1]))

import numpy as np

from deeplearning4j_tpu.datasets.mnist import MnistDataSetIterator
from deeplearning4j_tpu.util import serializer
from deeplearning4j_tpu.zoo.models import LeNet

net = LeNet(num_classes=10).init()
net.fit(MnistDataSetIterator(batch=128), epochs=2)
ev = net.evaluate(MnistDataSetIterator(batch=128, train=False))
print("LeNet accuracy:", ev.accuracy())
print(net.summary())

serializer.write_model(net, "/tmp/lenet.zip")
restored = serializer.restore_multi_layer_network("/tmp/lenet.zip")
np.testing.assert_allclose(restored.params_flat(), net.params_flat(),
                           rtol=1e-6)
print("serializer round-trip exact")
