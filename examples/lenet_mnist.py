import sys
sys.path.insert(0, str(__import__("pathlib").Path(__file__).resolve().parents[1]))
from deeplearning4j_tpu.conf import Activation, InputType
from deeplearning4j_tpu.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.conf.losses import LossMCXENT
from deeplearning4j_tpu.conf.multilayer import NeuralNetConfiguration
from deeplearning4j_tpu.conf.updaters import Adam
from deeplearning4j_tpu.datasets.mnist import MnistDataSetIterator
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

conf = (NeuralNetConfiguration.builder()
        .seed(123).updater(Adam(1e-3)).list()
        .layer(DenseLayer(n_out=256, activation=Activation.RELU))
        .layer(OutputLayer(n_out=10, activation=Activation.SOFTMAX,
                           loss_fn=LossMCXENT()))
        .set_input_type(InputType.convolutional(28, 28, 1))
        .build())
net = MultiLayerNetwork(conf).init()
net.fit(MnistDataSetIterator(batch=128), epochs=5)
acc = net.evaluate(MnistDataSetIterator(batch=128, train=False, num_examples=512)).accuracy()
print("quickstart accuracy:", acc)
assert acc > 0.6, acc
print("README QUICKSTART OK")
