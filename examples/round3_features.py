"""Round-3 feature tour: ComputationGraph truncated-BPTT + streaming,
mask resizing through strided convs, dashboard histograms, pipeline and
expert parallelism, and TF1 while-loop import.

Run anywhere (CPU works; set XLA_FLAGS=--xla_force_host_platform_device_count=8
JAX_PLATFORMS=cpu for the parallelism sections on one machine):

    python examples/round3_features.py
"""

import numpy as np

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.conf import Activation, InputType, WeightInit
from deeplearning4j_tpu.conf.layers_cnn import (
    Convolution1DLayer,
    ConvolutionMode,
)
from deeplearning4j_tpu.conf.layers_rnn import LSTM, RnnOutputLayer
from deeplearning4j_tpu.conf.losses import LossMCXENT
from deeplearning4j_tpu.conf.multilayer import (
    BackpropType,
    NeuralNetConfiguration,
)
from deeplearning4j_tpu.conf.updaters import Adam
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.graph import ComputationGraph

rng = np.random.default_rng(0)

# --- 1. ComputationGraph trains recurrent DAGs with truncated BPTT ---------
conf = (NeuralNetConfiguration.builder()
        .seed(1).updater(Adam(0.02)).weight_init(WeightInit.XAVIER)
        .graph_builder()
        .add_inputs("in")
        .set_input_types(InputType.recurrent(4, 40))
        .add_layer("rnn", LSTM(n_out=16), "in")
        .add_layer("rnn2", LSTM(n_out=12), "rnn")
        .add_layer("out", RnnOutputLayer(n_out=3,
                                         activation=Activation.SOFTMAX,
                                         loss_fn=LossMCXENT()), "rnn2")
        .set_outputs("out")
        .backprop_type(BackpropType.TRUNCATED_BPTT, fwd=5, back=3)
        .build())
net = ComputationGraph(conf).init()

x = rng.normal(size=(8, 40, 4)).astype(np.float32)
y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, (8, 40))]
mask = np.ones((8, 40), np.float32)
mask[0, 25:] = 0.0        # variable-length sample
for i in range(4):
    loss = net.fit_batch(DataSet(x, y, features_mask=mask,
                                 labels_mask=mask))
print(f"CG tBPTT loss after 4 batches (32 segments): {float(loss):.4f}")

# --- 1b. masks RESIZE through strided convs (standard backprop) ------------
mconf = (NeuralNetConfiguration.builder()
         .seed(3).updater(Adam(0.02)).weight_init(WeightInit.XAVIER)
         .graph_builder()
         .add_inputs("in")
         .set_input_types(InputType.recurrent(4, 40))
         .add_layer("conv", Convolution1DLayer(     # strided: T 40 -> 20
             n_out=8, kernel=2, stride1d=2, activation=Activation.TANH,
             convolution_mode=ConvolutionMode.TRUNCATE), "in")
         .add_layer("rnn", LSTM(n_out=16), "conv")
         .add_layer("out", RnnOutputLayer(n_out=3,
                                          activation=Activation.SOFTMAX,
                                          loss_fn=LossMCXENT()), "rnn")
         .set_outputs("out")
         .build())
mnet = ComputationGraph(mconf).init()
lmask = np.ones((8, 20), np.float32)   # labels at the conv-output rate
lmask[0, 13:] = 0.0
y20 = np.eye(3, dtype=np.float32)[rng.integers(0, 3, (8, 20))]
mloss = mnet.fit_batch(DataSet(x, y20, features_mask=mask,
                               labels_mask=lmask))
print(f"masked strided-conv graph loss: {float(mloss):.4f} "
      "(the input mask was max-pool-resized to the 20-step rate)")

# --- 2. streaming inference with per-vertex carries ------------------------
chain = (NeuralNetConfiguration.builder()
         .seed(2).updater(Adam(0.02))
         .graph_builder()
         .add_inputs("in")
         .set_input_types(InputType.recurrent(4, 40))
         .add_layer("rnn", LSTM(n_out=16), "in")
         .add_layer("out", RnnOutputLayer(n_out=3), "rnn")
         .set_outputs("out")
         .build())
snet = ComputationGraph(chain).init()
snet.rnn_clear_previous_state()
part1 = snet.rnn_time_step(x[:, :15])
part2 = snet.rnn_time_step(x[:, 15:])
full = snet.output(x)
err = float(jnp.max(jnp.abs(
    jnp.concatenate([part1, part2], axis=1) - full)))
print(f"rnn_time_step vs full forward max err: {err:.2e}")

# --- 3. dashboard histograms ------------------------------------------------
from deeplearning4j_tpu.ui import InMemoryStatsStorage, StatsListener, UIServer

storage = InMemoryStatsStorage()
probe = DataSet(x, y, features_mask=mask, labels_mask=mask)
net.set_listeners(StatsListener(storage, histograms=True,
                                sample_ds=probe))
net.fit_batch(probe)
net.fit_batch(probe)
panels = [k for k in storage.records()[-1]
          if k.endswith("_histograms")]
print("histogram panels recorded:", sorted(panels))
UIServer().attach(storage).render("/tmp/round3_dashboard.html")

# --- 4. pipeline + expert parallelism (needs >= 4 devices) ------------------
if len(jax.devices()) >= 4:
    from jax.sharding import Mesh

    from deeplearning4j_tpu.parallel.expert import (
        EXPERT_AXIS, moe_init, moe_train_step, shard_moe_params,
    )
    from deeplearning4j_tpu.parallel.pipeline import (
        STAGE_AXIS, pipeline_train_step, stack_stage_params,
    )

    devs = np.array(jax.devices()[:4])
    pmesh = Mesh(devs, (STAGE_AXIS,))
    stages = [{"w": 0.3 * jax.random.normal(jax.random.PRNGKey(s), (8, 8)),
               "b": jnp.zeros((8,))} for s in range(4)]
    sp = stack_stage_params(stages, pmesh)
    xm = jnp.asarray(rng.normal(size=(8, 4, 8)).astype(np.float32))
    ym = jnp.asarray(rng.normal(size=(8, 4, 8)).astype(np.float32))
    pstep = pipeline_train_step(
        lambda p, x: jnp.tanh(x @ p["w"] + p["b"]),
        lambda o, t: jnp.mean((o - t) ** 2), 4, 8, pmesh, lr=0.1)
    for _ in range(5):
        sp, ploss = pstep(sp, xm, ym)
    print(f"GPipe pipeline (4 stages x 8 microbatches) loss: "
          f"{float(ploss):.4f}")

    emesh = Mesh(devs, (EXPERT_AXIS,))
    ep = shard_moe_params(moe_init(jax.random.PRNGKey(7), 8, 32, 4), emesh)
    xt = jnp.asarray(rng.normal(size=(32, 8)).astype(np.float32))
    tt = jnp.asarray(rng.normal(size=(32, 8)).astype(np.float32))
    estep = moe_train_step(4, capacity=32, mesh=emesh, lr=0.05)
    for _ in range(5):
        ep, eloss = estep(ep, xt, tt)
    print(f"MoE expert-parallel (4 experts, all_to_all) loss: "
          f"{float(eloss):.4f}")

# --- 5. TF1 while-loop frame import ----------------------------------------
from deeplearning4j_tpu.imports.protos import tf_graph_pb2 as pb
from deeplearning4j_tpu.imports.tf import TFGraphMapper


def _const(g, name, v):
    n = g.node.add()
    n.name, n.op = name, "Const"
    n.attr["dtype"].type = pb.DT_FLOAT
    t = n.attr["value"].tensor
    t.dtype = pb.DT_FLOAT
    t.tensor_content = np.asarray(v, np.float32).tobytes()


def _n(g, name, op, *inputs, **attrs):
    n = g.node.add()
    n.name, n.op = name, op
    n.input.extend(inputs)
    for k, v in attrs.items():
        n.attr[k].s = v
    return n


g = pb.GraphDef()
_const(g, "i0", 0.0)
_const(g, "acc0", 1.0)
_const(g, "lim", 5.0)
_n(g, "enter_i", "Enter", "i0", frame_name=b"L")
_n(g, "enter_acc", "Enter", "acc0", frame_name=b"L")
e = _n(g, "enter_lim", "Enter", "lim", frame_name=b"L")
e.attr["is_constant"].b = True
_n(g, "merge_i", "Merge", "enter_i", "next_i")
_n(g, "merge_acc", "Merge", "enter_acc", "next_acc")
_n(g, "less", "Less", "merge_i", "enter_lim")
_n(g, "cond", "LoopCond", "less")
_n(g, "sw_i", "Switch", "merge_i", "cond")
_n(g, "sw_acc", "Switch", "merge_acc", "cond")
_const(g, "one", 1.0)
_n(g, "inc", "Add", "sw_i:1", "one")
_n(g, "dbl", "Add", "sw_acc:1", "sw_acc:1")
_n(g, "next_i", "NextIteration", "inc")
_n(g, "next_acc", "NextIteration", "dbl")
_n(g, "exit_acc", "Exit", "sw_acc")
sd = TFGraphMapper.import_graph(g.SerializeToString())
acc = float(np.asarray(sd.output({}, "exit_acc")["exit_acc"]))
print(f"TF1 while-loop frames import: 2^5 = {acc:.0f}")
