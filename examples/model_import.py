"""Model import: Keras HDF5, TF frozen GraphDef, ONNX — all without the
source frameworks installed."""
import json
import sys
sys.path.insert(0, str(__import__("pathlib").Path(__file__).resolve().parents[1]))

import h5py
import numpy as np

from deeplearning4j_tpu.imports import OnnxGraphMapper, TFGraphMapper
from deeplearning4j_tpu.imports.protos import tf_graph_pb2 as pb
from deeplearning4j_tpu.modelimport import KerasModelImport

rng = np.random.default_rng(0)

# --- Keras Sequential h5 -------------------------------------------------
w = rng.normal(size=(4, 3)).astype(np.float32)
b = np.zeros(3, np.float32)
cfg = {"class_name": "Sequential", "config": {"layers": [
    {"class_name": "Dense", "config": {
        "name": "dense", "units": 3, "activation": "softmax",
        "use_bias": True, "batch_input_shape": [None, 4]}}]}}
with h5py.File("/tmp/example_keras.h5", "w") as f:
    f.attrs["model_config"] = json.dumps(cfg)
    g = f.create_group("model_weights").create_group("dense").create_group(
        "dense")
    g.create_dataset("kernel", data=w)
    g.create_dataset("bias", data=b)
net = KerasModelImport.import_keras_sequential_model_and_weights(
    "/tmp/example_keras.h5")
print("keras import output:", np.asarray(
    net.output(rng.normal(size=(2, 4)).astype(np.float32))).shape)

# --- TF frozen GraphDef --------------------------------------------------
g = pb.GraphDef()
n = g.node.add(); n.name = "x"; n.op = "Placeholder"
n.attr["dtype"].type = pb.DT_FLOAT
for d in (-1, 4):
    n.attr["shape"].shape.dim.add().size = d
c = g.node.add(); c.name = "w"; c.op = "Const"
c.attr["dtype"].type = pb.DT_FLOAT
t = c.attr["value"].tensor; t.dtype = pb.DT_FLOAT
t.tensor_shape.dim.add().size = 4
t.tensor_shape.dim.add().size = 2
t.tensor_content = w[:, :2].tobytes()
mm = g.node.add(); mm.name = "y"; mm.op = "MatMul"
mm.input.extend(["x", "w"])
sd = TFGraphMapper.import_graph(g.SerializeToString())
out = sd.output({"x": rng.normal(size=(2, 4)).astype(np.float32)}, "y")
print("tf import output:", np.asarray(out["y"]).shape)

# --- ONNX ModelProto -----------------------------------------------------
from deeplearning4j_tpu.imports.protos import onnx_model_pb2 as ox

m = ox.ModelProto()
m.ir_version = 8
m.opset_import.add().version = 13
og = m.graph
vi = og.input.add()
vi.name = "x"
tt = vi.type.tensor_type
tt.elem_type = 1
d = tt.shape.dim.add(); d.dim_param = "N"
d = tt.shape.dim.add(); d.dim_value = 4
t = og.initializer.add()
t.name = "w"
t.data_type = 1
t.dims.extend([4, 3])
t.raw_data = w.tobytes()
node = og.node.add()
node.op_type = "Gemm"
node.input.extend(["x", "w"])
node.output.append("y")
node2 = og.node.add()
node2.op_type = "Softmax"
node2.input.append("y")
node2.output.append("p")
sd2 = OnnxGraphMapper.import_graph(m.SerializeToString())
out2 = sd2.output({"x": rng.normal(size=(2, 4)).astype(np.float32)}, "p")
print("onnx import output:", np.asarray(out2["p"]).shape)
print("ALL IMPORT PATHS OK")
