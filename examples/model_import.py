"""Model import: Keras HDF5, TF frozen GraphDef, ONNX — all without the
source frameworks installed."""
import json
import sys
sys.path.insert(0, str(__import__("pathlib").Path(__file__).resolve().parents[1]))

import h5py
import numpy as np

from deeplearning4j_tpu.imports import OnnxGraphMapper, TFGraphMapper
from deeplearning4j_tpu.imports.protos import tf_graph_pb2 as pb
from deeplearning4j_tpu.modelimport import KerasModelImport

rng = np.random.default_rng(0)

# --- Keras Sequential h5 -------------------------------------------------
w = rng.normal(size=(4, 3)).astype(np.float32)
b = np.zeros(3, np.float32)
cfg = {"class_name": "Sequential", "config": {"layers": [
    {"class_name": "Dense", "config": {
        "name": "dense", "units": 3, "activation": "softmax",
        "use_bias": True, "batch_input_shape": [None, 4]}}]}}
with h5py.File("/tmp/example_keras.h5", "w") as f:
    f.attrs["model_config"] = json.dumps(cfg)
    g = f.create_group("model_weights").create_group("dense").create_group(
        "dense")
    g.create_dataset("kernel", data=w)
    g.create_dataset("bias", data=b)
net = KerasModelImport.import_keras_sequential_model_and_weights(
    "/tmp/example_keras.h5")
print("keras import output:", np.asarray(
    net.output(rng.normal(size=(2, 4)).astype(np.float32))).shape)

# --- TF frozen GraphDef --------------------------------------------------
g = pb.GraphDef()
n = g.node.add(); n.name = "x"; n.op = "Placeholder"
n.attr["dtype"].type = pb.DT_FLOAT
for d in (-1, 4):
    n.attr["shape"].shape.dim.add().size = d
c = g.node.add(); c.name = "w"; c.op = "Const"
c.attr["dtype"].type = pb.DT_FLOAT
t = c.attr["value"].tensor; t.dtype = pb.DT_FLOAT
t.tensor_shape.dim.add().size = 4
t.tensor_shape.dim.add().size = 2
t.tensor_content = w[:, :2].tobytes()
mm = g.node.add(); mm.name = "y"; mm.op = "MatMul"
mm.input.extend(["x", "w"])
sd = TFGraphMapper.import_graph(g.SerializeToString())
out = sd.output({"x": rng.normal(size=(2, 4)).astype(np.float32)}, "y")
print("tf import output:", np.asarray(out["y"]).shape)
print("ALL IMPORT PATHS OK")
