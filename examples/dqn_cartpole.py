"""DQN on CartPole (reference rl4j QLearningDiscreteDense example)."""
import sys
sys.path.insert(0, str(__import__("pathlib").Path(__file__).resolve().parents[1]))

from deeplearning4j_tpu.rl4j import (CartPole, QLearningConfiguration,
                                     QLearningDiscreteDense)

cfg = QLearningConfiguration(
    seed=3, max_step=6000, max_epoch_step=200, batch_size=64,
    update_start=200, target_dqn_update_freq=100, epsilon_nb_step=3000,
    learning_rate=5e-4)
dqn = QLearningDiscreteDense(CartPole(max_steps=200, seed=3), cfg)
dqn.train()
print("greedy episode reward:", dqn.play(episodes=3))
