import sys
sys.path.insert(0, str(__import__("pathlib").Path(__file__).resolve().parents[1]))
import numpy as np, jax
print("backend:", jax.default_backend())

# Word2Vec on TPU
from deeplearning4j_tpu.nlp import Word2Vec, WordVectorSerializer, Glove, ParagraphVectors
rng = np.random.default_rng(0)
animals = ["cat","dog","pet","fur","tail"]; cars = ["car","road","drive","wheel","engine"]
sents = [" ".join(rng.choice(animals if rng.random()<.5 else cars, size=6)) for _ in range(300)]
w2v = Word2Vec(layer_size=24, window_size=3, min_word_frequency=2, epochs=3, batch_size=256, seed=1).fit(sents)
print("sim(cat,dog) %.3f  sim(cat,road) %.3f" % (w2v.similarity("cat","dog"), w2v.similarity("cat","road")))
assert w2v.similarity("cat","dog") > w2v.similarity("cat","road")
WordVectorSerializer.write_word2vec_model(w2v, "/tmp/w2v.zip")
back = WordVectorSerializer.read_word2vec_model("/tmp/w2v.zip")
assert abs(back.similarity("cat","dog") - w2v.similarity("cat","dog")) < 1e-6
print("w2v serializer ok")

g = Glove(layer_size=16, window_size=3, min_word_frequency=2, epochs=40).fit(sents)
assert g.similarity("cat","dog") > g.similarity("cat","road")
print("glove ok")

print("NLP EXAMPLE DONE")
