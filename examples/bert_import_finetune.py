"""BERT-style frozen-graph import + fine-tune (BASELINE config #5).

Builds a small transformer-encoder GraphDef the way TF freezes BERT
(Gather embeddings, BatchMatMul attention, decomposed-Erf GELU, layernorm
from Mean/SquaredDifference/Rsqrt, StridedSlice CLS pooler), imports it
with TFGraphMapper, converts the head + attention weights to trainables,
and fine-tunes with ``sd.fit`` — the reference's
``importGraph`` -> ``convertToVariable`` -> ``fit`` flow.
"""
import sys
sys.path.insert(0, str(__import__("pathlib").Path(__file__).resolve().parents[1]))

import numpy as np

sys.path.insert(0, str(__import__("pathlib").Path(__file__).resolve().parents[1] / "tests"))
from test_tf_import import _build_mini_bert  # fixture builder doubles as demo

from deeplearning4j_tpu.conf.updaters import Adam
from deeplearning4j_tpu.imports.tf import TFGraphMapper
from deeplearning4j_tpu.samediff import TrainingConfig
from deeplearning4j_tpu.samediff.core import SDVariable

rng = np.random.default_rng(0)
graph, _ = _build_mini_bert(rng)
sd = TFGraphMapper.import_graph(graph.SerializeToString())
print(f"imported: {len(sd.ops)} ops, {len(sd.variables)} variables")

for name in ("w_cls", "b_cls", "wq", "wk", "wv", "wo"):
    SDVariable(sd, name).convert_to_variable()
labels = sd.placeholder("labels", shape=(None, 3))
sd.loss.softmaxCrossEntropy(labels, SDVariable(sd, "logits"), name="loss")
sd.set_training_config(TrainingConfig.builder()
                       .updater(Adam(learning_rate=0.01))
                       .data_set_feature_mapping("ids")
                       .data_set_label_mapping("labels").build())

ids = rng.integers(0, 50, (64, 8)).astype(np.int32)
y = np.eye(3, dtype=np.float32)[ids.sum(1) % 3]
hist = None
for epoch in range(40):
    hist = sd.fit(features=ids, labels=y)
print("fine-tune loss:", hist.loss_curve[-1])
preds = np.asarray(sd.output({"ids": ids}, "logits")["logits"]).argmax(1)
print("train accuracy:", (preds == ids.sum(1) % 3).mean())
