"""Transfer learning: freeze a trained front, swap the head, featurize."""
import sys
sys.path.insert(0, str(__import__("pathlib").Path(__file__).resolve().parents[1]))

import numpy as np

from deeplearning4j_tpu.conf import Activation, InputType
from deeplearning4j_tpu.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.conf.losses import LossMCXENT
from deeplearning4j_tpu.conf.multilayer import NeuralNetConfiguration
from deeplearning4j_tpu.conf.updaters import Adam, Sgd
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.transferlearning import (
    FineTuneConfiguration, TransferLearning, TransferLearningHelper)

rng = np.random.default_rng(0)
conf = (NeuralNetConfiguration.builder().seed(7).updater(Adam(1e-2)).list()
        .layer(DenseLayer(n_out=16, activation=Activation.TANH))
        .layer(OutputLayer(n_out=3, activation=Activation.SOFTMAX,
                           loss_fn=LossMCXENT()))
        .set_input_type(InputType.feed_forward(4)).build())
base = MultiLayerNetwork(conf).init()
x = rng.normal(size=(96, 4)).astype(np.float32)
base.fit(x, np.eye(3, dtype=np.float32)[rng.integers(0, 3, 96)], epochs=5)

# freeze the feature layer, put a fresh 5-class head on
t_net = (TransferLearning.Builder(base)
         .fine_tune_configuration(FineTuneConfiguration(updater=Sgd(0.05)))
         .set_feature_extractor(0)
         .remove_output_layer()
         .add_layer(OutputLayer(n_out=5, activation=Activation.SOFTMAX,
                                loss_fn=LossMCXENT()))
         .build())
y5 = np.eye(5, dtype=np.float32)[rng.integers(0, 5, 96)]
helper = TransferLearningHelper(t_net)
feat = helper.featurize(DataSet(x, y5))
for _ in range(20):
    helper.fit_featurized(feat)
print("tail score after featurized training:",
      helper.unfrozen_mln().score_value)
