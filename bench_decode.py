"""Closed-loop token-throughput benchmark for continuous-batching decode
(ISSUE 11 acceptance): a mixed prompt/output-length workload runs twice
through the SAME compiled KV-cache executables,

  sequential — one request at a time to completion (occupancy 1: the
               per-request generation loop every pre-continuous server
               runs, ``TransformerDecoder.generate``), and
  continuous — the iteration-level scheduler
               (``parallel.generation.GenerationEngine``): sequences
               join and retire the running batch every K-token window,
               so freed KV rows never sit idle.

Reports aggregate tokens/s for both modes, the speedup, the prefill vs
decode wall-time split, p50/p95 per-token latency and time-to-first-
token, recompiles after warmup (must be 0), and a greedy token-identity
check (continuous output must equal sequential bit-for-bit). Writes
``bench_decode.json``; ``BENCH_decode_r01.json`` is the committed
round-1 baseline.

Methodology + honest caveats (docs/serving.md has the full discussion):
- CPU proxy by default — absolute tokens/s is meaningless off-chip; the
  CONTRAST is the result. Both modes share every executable, so the
  speedup isolates scheduling, not kernels.
- The sequential baseline still pads its single row to the same
  ``max_batch``-wide decode executable: per-step device cost is roughly
  equal across modes on the CPU proxy, and the continuous win is pure
  occupancy (more sequences advanced per identically-priced window).
  On a real chip a batch-1 decode executable would be cheaper per step,
  but it would also recompile per occupancy level — exactly the
  request-granularity pathology this subsystem removes.
- ``--smoke`` (the ``make decode-smoke`` leg) runs a small workload and
  asserts speedup > 1, token identity, and zero recompiles.
"""

import argparse
import json
import os
import random
import time


def _pin_cpu():
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    import jax

    try:
        jax.config.update("jax_platforms",
                          os.environ.get("JAX_PLATFORMS", "cpu"))
    except Exception:
        pass


def _workload(n, vocab, max_len, seed):
    """Mixed closed-loop workload: prompts 2..max_len//3 tokens, outputs
    3..max_len//2 tokens, lengths drawn from a seeded stream so the two
    modes (and two rounds) see identical traffic."""
    rng = random.Random(seed)
    reqs = []
    for _ in range(n):
        plen = rng.randint(2, max_len // 3)
        mnew = rng.randint(3, min(max_len // 2, max_len - plen))
        prompt = [rng.randrange(vocab) for _ in range(plen)]
        reqs.append((prompt, mnew))
    return reqs


def _quantiles(snap, name):
    h = snap.get(name)
    if not isinstance(h, dict) or not h.get("count"):
        return None
    return {"p50": h["p50"], "p95": h["p95"], "count": h["count"]}


def bench(args):
    if not args.tpu:
        _pin_cpu()
    from deeplearning4j_tpu.optimize import aot_cache
    from deeplearning4j_tpu.parallel.generation import (
        GenerationConfig,
        GenerationEngine,
    )
    from deeplearning4j_tpu.telemetry import REGISTRY
    from deeplearning4j_tpu.zoo.graphs import TransformerEncoder

    model = TransformerEncoder(
        vocab_size=args.vocab, embed_dim=args.embed, n_heads=args.heads,
        n_layers=args.layers, max_len=args.max_len, causal=True,
        lm_head=True, seed=123)
    dec = model.decoder(max_batch=args.max_batch,
                        kv_bucket_min=args.max_len // 4,
                        prompt_bucket_min=8)
    eng = GenerationEngine(dec, GenerationConfig(
        max_batch=args.max_batch, fused_steps=args.fused_steps,
        kv_bucket_min=args.max_len // 4, prompt_bucket_min=8))
    warm = eng.warmup()
    print(f"warmup: {warm['compiled']} executables in "
          f"{warm['compile_seconds']}s "
          f"(kv {warm['kv_buckets']}, prompt {warm['prompt_buckets']}, "
          f"join {warm['join_buckets']}, K {warm['fused_steps']})")
    reqs = _workload(args.requests, args.vocab, args.max_len, args.seed)
    miss0 = aot_cache.stats()["misses"]

    # sequential per-request generation (the baseline being replaced)
    t0 = time.monotonic()
    seq_out = [dec.generate(p, mn, fused_steps=args.fused_steps)
               for p, mn in reqs]
    seq_s = time.monotonic() - t0
    seq_tokens = sum(len(o) for o in seq_out)

    # continuous: submit everything, the engine streams requests through
    # max_batch rows at token granularity (the per-token / TTFT
    # histograms below are engine-only series, so they describe this
    # mode alone)
    st0 = eng.stats()
    t0 = time.monotonic()
    handles = [eng.submit(p, max_new_tokens=mn) for p, mn in reqs]
    cont_out = [eng.result(h) for h in handles]
    cont_s = time.monotonic() - t0
    cont_tokens = sum(len(o) for o in cont_out)
    st1 = eng.stats()
    snap1 = REGISTRY.snapshot(run_collectors=False)

    identical = cont_out == seq_out
    recompiles = aot_cache.stats()["misses"] - miss0
    prefill_s = st1["prefill_seconds"] - st0["prefill_seconds"]
    decode_s = st1["decode_seconds"] - st0["decode_seconds"]
    results = {
        "bench": "decode_continuous_batching",
        "mode": "cpu-proxy" if not args.tpu else "tpu",
        "model": {"vocab": args.vocab, "embed": args.embed,
                  "heads": args.heads, "layers": args.layers,
                  "max_len": args.max_len},
        "engine": {"max_batch": args.max_batch,
                   "fused_steps": args.fused_steps,
                   "kv_buckets": warm["kv_buckets"],
                   "warmup_executables": warm["compiled"],
                   "warmup_compile_seconds": warm["compile_seconds"]},
        "workload": {"requests": args.requests, "seed": args.seed,
                     "total_tokens": cont_tokens},
        "sequential": {"tokens_per_sec": round(seq_tokens / seq_s, 1),
                       "wall_seconds": round(seq_s, 3),
                       "tokens": seq_tokens},
        "continuous": {"tokens_per_sec": round(cont_tokens / cont_s, 1),
                       "wall_seconds": round(cont_s, 3),
                       "tokens": cont_tokens,
                       "prefill_seconds": round(prefill_s, 3),
                       "decode_seconds": round(decode_s, 3),
                       "prefill_fraction": round(
                           prefill_s / max(prefill_s + decode_s, 1e-9), 3)},
        "speedup": round((cont_tokens / cont_s) / (seq_tokens / seq_s), 2),
        "per_token_latency_s": _quantiles(snap1,
                                          "dl4j_decode_token_seconds"),
        "time_to_first_token_s": _quantiles(
            snap1, "dl4j_decode_first_token_seconds"),
        "greedy_identical_to_sequential": identical,
        "recompiles_after_warmup": recompiles,
    }
    eng.close()
    print(json.dumps(results, indent=2))
    with open(args.out, "w") as f:
        json.dump(results, f, indent=2)
    print(f"wrote {args.out}")
    if args.smoke:
        assert identical, "continuous greedy output != sequential reference"
        assert recompiles == 0, f"{recompiles} recompiles after warmup"
        assert results["speedup"] > 1.0, \
            f"continuous batching slower than sequential " \
            f"(speedup {results['speedup']})"
        print(f"decode-smoke OK: speedup {results['speedup']}x, "
              f"0 recompiles, token-identical")
    return 0


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--requests", type=int, default=32)
    ap.add_argument("--max-batch", type=int, default=8)
    ap.add_argument("--fused-steps", type=int, default=4)
    ap.add_argument("--vocab", type=int, default=128)
    ap.add_argument("--embed", type=int, default=64)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--max-len", type=int, default=128)
    ap.add_argument("--seed", type=int, default=11)
    ap.add_argument("--out", default="bench_decode.json")
    ap.add_argument("--tpu", action="store_true",
                    help="run on the real chip instead of the CPU proxy")
    ap.add_argument("--smoke", action="store_true",
                    help="small workload + assertions (make decode-smoke)")
    args = ap.parse_args()
    if args.smoke:
        args.requests = min(args.requests, 12)
        args.vocab, args.embed, args.max_len = 32, 16, 48
        args.max_batch = min(args.max_batch, 4)
    if not args.tpu:
        _pin_cpu()
    return bench(args)


if __name__ == "__main__":
    raise SystemExit(main())
