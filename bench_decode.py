"""Closed-loop token-throughput benchmark for the generation engine
(ISSUE 11 continuous batching, ISSUE 16 prefix caching + speculative
decoding): a SHARED-PREFIX workload (every prompt opens with the same
system-prompt-style token block) runs through the same compiled KV-cache
executables in up to five legs,

  sequential  — one request at a time to completion
                (``TransformerDecoder.generate``),
  continuous  — the iteration-level scheduler
                (``parallel.generation.GenerationEngine``),
  prefix      — continuous + the radix-tree prefix cache
                (``--prefix-cache``): hits attach cached KV pages and
                prefill only the suffix,
  speculative — continuous + draft-model speculation
                (``--speculative``): a distilled 1-layer draft proposes
                ``--spec-tokens`` tokens per iteration, the target
                scores all K+1 positions in one ``spec_verify`` launch,
  combined    — prefix cache + speculation together (both flags),
  paged       — continuous over a ``use_kernels=True`` model
                (``--paged``): flash prefill + paged decode attention
                through the Pallas kernel registry, tuned before
                warmup; on the CPU proxy the kernel bodies run the
                Pallas interpreter, so this leg pins token identity +
                zero recompiles + the tuned winner set, not speed.

Every engine leg runs the workload twice: an UNTIMED settle pass that
pays each executable's one-time first-dispatch cost (and, in prefix
legs, seeds the trie — the timed pass then measures steady-state hits),
then the timed pass. The sequential baseline gets the same two-pass
treatment. Per leg the report carries tokens/s, wall seconds, the
prefill/decode split, TTFT quantiles (first-wave TTFT isolates prefill
latency from queue wait), greedy token-identity against the sequential
reference, recompiles after warmup (must be 0 across BOTH passes —
mixed hit/miss and accept/reject traffic included), and acceptance rate
for speculative legs. Writes ``bench_decode.json``;
``BENCH_decode_r02.json`` is the committed round-2 snapshot and
``BENCH_decode_r01.json`` the round-1 continuous-batching baseline the
speculative leg is judged against.

Methodology + honest caveats (docs/serving.md has the full discussion):
- CPU proxy by default — absolute tokens/s is meaningless off-chip; the
  CONTRAST is the result. All legs share every executable, so the
  deltas isolate scheduling, cache reuse, and launch economics, not
  kernels.
- The draft model is DISTILLED on the sequential leg's own outputs
  (next-token cross-entropy on the exact target streams, full-length
  position-aligned windows). The benchmark workload is deliberately
  low-entropy — greedy decode settles into attractor cycles a 1-layer
  draft can learn — so acceptance is high. Real-text acceptance depends
  entirely on the draft/target fit; the number reported here
  characterizes the ENGINE, not language-model speculation at large.
  ``--smoke`` swaps the distilled draft for an oracle draft (same
  config + seed as the target) so the machinery asserts don't depend
  on a training run.
- On the dispatch-bound CPU proxy a speculative window costs two
  launches (fused draft window + wide verify) against one plain fused
  window, so speculation only wins with draft K well past
  ``fused_steps`` and high acceptance — which is exactly the regime a
  real serving draft targets. TTFT wins for the prefix leg are
  suffix-only prefill vs full prefill.
"""

import argparse
import json
import os
import random
import time


def _pin_cpu():
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    import jax

    try:
        jax.config.update("jax_platforms",
                          os.environ.get("JAX_PLATFORMS", "cpu"))
    except Exception:
        pass


def _workload(n, vocab, max_len, seed):
    """Shared-prefix closed-loop workload: every prompt opens with the
    same ``max_len // 4``-token block (the system-prompt / few-shot
    template pattern the prefix cache exists for — long enough that a
    cold prefill pays a prompt launch two buckets wider than the
    suffix-only hit path), followed by a per-request suffix of
    2..max_len//16 tokens; outputs fill most of the remaining context
    so decode dominates and speculative windows keep runway short of
    the context limit. Lengths come from a seeded stream so every leg
    sees identical traffic."""
    rng = random.Random(seed)
    shared = [rng.randrange(vocab) for _ in range(max(4, max_len // 4))]
    reqs = []
    for _ in range(n):
        plen = rng.randint(2, max(2, max_len // 16))
        prompt = shared + [rng.randrange(vocab) for _ in range(plen)]
        lo = max(3, max_len * 3 // 8)
        hi = max(lo, max_len * 5 // 8)
        mnew = max(3, min(rng.randint(lo, hi), max_len - len(prompt) - 1))
        reqs.append((prompt, mnew))
    return reqs


def _quantiles(vals):
    if not vals:
        return None
    s = sorted(vals)
    pick = lambda q: s[min(len(s) - 1, int(q * (len(s) - 1) + 0.5))]  # noqa: E731
    return {"p50": round(pick(0.50), 4), "p95": round(pick(0.95), 4),
            "count": len(s)}


def _distill_draft(model_args, seqs, epochs):
    """Distill the draft on the target's own greedy streams: a 1-layer
    transformer half the target's width, trained with next-token
    cross-entropy on full-length POSITION-ALIGNED windows (training on
    shifted sub-windows leaves the later position embeddings untrained
    and collapses acceptance). Zero label rows past each sequence's end
    contribute zero loss — a free padding mask under MCXENT."""
    import numpy as np
    from deeplearning4j_tpu.zoo.graphs import TransformerEncoder

    a = model_args
    conf = TransformerEncoder(
        vocab_size=a.vocab, embed_dim=max(8, a.embed // 2),
        n_heads=max(1, a.heads // 2), n_layers=1, max_len=a.max_len,
        causal=True, lm_head=True, seed=7)
    net = conf.init()
    t = max(len(s) for s in seqs) - 1
    feats, labs = [], []
    for s in seqs:
        w = s + [0] * (t + 1 - len(s))
        feats.append(w[:t])
        oh = np.zeros((t, a.vocab), np.float32)
        n = len(s) - 1
        oh[np.arange(n), w[1:n + 1]] = 1.0
        labs.append(oh)
    feats = np.asarray(feats, np.int32)
    labs = np.asarray(labs, np.float32)
    t0 = time.monotonic()
    net.fit(feats, labs, epochs=epochs)
    fit_s = time.monotonic() - t0
    pred = np.asarray(net.output(feats)).argmax(-1)
    mask = labs.sum(-1) > 0
    agreement = float((pred == labs.argmax(-1))[mask].mean())
    dd = conf.decoder(net, max_batch=a.max_batch,
                      kv_bucket_min=a.max_len // 4, prompt_bucket_min=8)
    return dd, {"layers": 1, "embed": max(8, a.embed // 2),
                "epochs": epochs, "fit_seconds": round(fit_s, 1),
                "teacher_forced_agreement": round(agreement, 4),
                "kind": "distilled"}


def _oracle_draft(model_args):
    """Smoke-mode draft: the target's own config and seed — agreement is
    1.0 by construction, so the machinery asserts (acceptance recorded,
    identity, zero recompiles) don't hinge on a training run."""
    from deeplearning4j_tpu.zoo.graphs import TransformerEncoder

    a = model_args
    conf = TransformerEncoder(
        vocab_size=a.vocab, embed_dim=a.embed, n_heads=a.heads,
        n_layers=a.layers, max_len=a.max_len, causal=True,
        lm_head=True, seed=123)
    dd = conf.decoder(max_batch=a.max_batch, kv_bucket_min=a.max_len // 4,
                      prompt_bucket_min=8)
    return dd, {"kind": "oracle (same config+seed as target)"}


def _run_engine_leg(name, model, args, reqs, seq_out, draft=None,
                    prefix=False, tune_kernels=False):
    from deeplearning4j_tpu.optimize import aot_cache
    from deeplearning4j_tpu.parallel.generation import (
        GenerationConfig,
        GenerationEngine,
    )

    cfg = GenerationConfig(
        max_batch=args.max_batch, fused_steps=args.fused_steps,
        kv_bucket_min=args.max_len // 4, prompt_bucket_min=8,
        draft_conf=draft, spec_tokens=args.spec_tokens if draft else None,
        prefix_cache=prefix, prefix_page=args.prefix_page)
    dec = model.decoder(max_batch=args.max_batch,
                        kv_bucket_min=args.max_len // 4,
                        prompt_bucket_min=8)
    tune_info = None
    if tune_kernels:
        # tune BEFORE warmup: a later tune would bump the digest and
        # re-mint every kern:-keyed executable the warmup just built
        from deeplearning4j_tpu import kernels

        t0 = time.monotonic()
        tuned = kernels.autotune_decoder(dec, max_candidates=2, trials=1)
        tune_info = {"tuned_envelopes": len(tuned),
                     "autotune_seconds": round(time.monotonic() - t0, 2)}
    eng = GenerationEngine(dec, cfg)
    warm = eng.warmup()
    miss0 = aot_cache.stats()["misses"]

    # settle pass: identical traffic, untimed — one-time first-dispatch
    # costs land here, and prefix legs seed the trie so the timed
    # passes measure steady-state hits
    for h in [eng.submit(p, max_new_tokens=mn) for p, mn in reqs]:
        eng.result(h)

    # best of N timed passes: CPU-proxy wall clock is noisy (shared
    # host, XLA thread-pool contention), so each leg re-runs the same
    # traffic and reports its best pass with every pass recorded
    passes = []
    identical = True
    best = None
    for _ in range(max(1, args.passes)):
        st0 = eng.stats()
        t0 = time.monotonic()
        handles = [eng.submit(p, max_new_tokens=mn) for p, mn in reqs]
        out = [eng.result(h) for h in handles]
        wall = time.monotonic() - t0
        tokens = sum(len(o) for o in out)
        st1 = eng.stats()
        identical = identical and out == seq_out
        passes.append({"wall": wall, "tokens": tokens, "st0": st0,
                       "st1": st1, "handles": handles})
        if best is None or tokens / wall > best["tokens"] / best["wall"]:
            best = passes[-1]

    wall, tokens = best["wall"], best["tokens"]
    st0, st1, handles = best["st0"], best["st1"], best["handles"]
    recompiles = aot_cache.stats()["misses"] - miss0
    ttft_all = [h.t_first - h.t0 for h in handles if h.t_first is not None]
    first_wave = handles[:args.max_batch]
    ttft_wave = [h.t_first - h.t0 for h in first_wave
                 if h.t_first is not None]
    leg = {
        "tokens_per_sec": round(tokens / wall, 1),
        "wall_seconds": round(wall, 3),
        "tokens": tokens,
        "pass_tokens_per_sec": [round(p["tokens"] / p["wall"], 1)
                                for p in passes],
        "prefill_seconds": round(
            st1["prefill_seconds"] - st0["prefill_seconds"], 3),
        "decode_seconds": round(
            st1["decode_seconds"] - st0["decode_seconds"], 3),
        "ttft_s": _quantiles(ttft_all),
        "ttft_first_wave_s": _quantiles(ttft_wave),
        "greedy_identical_to_sequential": identical,
        "recompiles_after_warmup": recompiles,
        "warmup_executables": warm["compiled"],
        "warmup_compile_seconds": warm["compile_seconds"],
    }
    if draft is not None:
        leg["speculative"] = st1["speculative"]
        leg["spec_tokens"] = args.spec_tokens
    if prefix:
        pc = dict(st1["prefix_cache"])
        leg["prefix_cache"] = pc
    if tune_kernels:
        leg["kernels"] = dict(st1["kernels"])
        leg["kernels"].update(tune_info)
    eng.close()
    print(f"{name}: {leg['tokens_per_sec']} tok/s, identical={identical}, "
          f"recompiles={recompiles}"
          + (f", acceptance="
             f"{leg['speculative']['acceptance']:.3f}" if draft else "")
          + (f", hits={leg['prefix_cache']['hits']}" if prefix else ""))
    return leg


def bench_traces(args):
    """``--traces``: request-tracing overhead A/B on the continuous leg.
    The identical shared-prefix workload runs through two engines —
    tracing OFF (one boolean check per ``start_trace``) then ON with
    ``sample_every=1`` (every request carries its span through queued →
    join → prefill/prefix_attach → first_token → decode_window* →
    done) — and the JSON carries both tokens/s, the overhead fraction
    against ``--trace-overhead-budget``, the trace-derived queue-wait /
    decode-window breakdown, and the zero-recompile check for BOTH
    modes: tracing is host-side monotonic_ns + list appends and must
    never mint an AOT key. Token identity vs the sequential reference
    is asserted in both modes too — tracing must not perturb
    scheduling-order-sensitive outputs."""
    if not args.tpu:
        _pin_cpu()
    from deeplearning4j_tpu.telemetry import tracing
    from deeplearning4j_tpu.zoo.graphs import TransformerEncoder

    model = TransformerEncoder(
        vocab_size=args.vocab, embed_dim=args.embed, n_heads=args.heads,
        n_layers=args.layers, max_len=args.max_len, causal=True,
        lm_head=True, seed=123)
    dec = model.decoder(max_batch=args.max_batch,
                        kv_bucket_min=args.max_len // 4,
                        prompt_bucket_min=8)
    reqs = _workload(args.requests, args.vocab, args.max_len, args.seed)
    seq_out = [dec.generate(p, mn, fused_steps=args.fused_steps)
               for p, mn in reqs]

    tracing.disable()
    off = _run_engine_leg("traces-off", model, args, reqs, seq_out)
    tracing.enable(seed=7, sample_every=1)
    on = _run_engine_leg("traces-on", model, args, reqs, seq_out)
    on["sampler"] = tracing.stats()
    on["stage_breakdown"] = {
        k: v for k, v in tracing.stage_breakdown().items() if v is not None}
    tracing.disable()

    overhead = round(
        1.0 - on["tokens_per_sec"] / max(off["tokens_per_sec"], 1e-9), 4)
    results = {
        "bench": "decode_tracing_overhead",
        "mode": "cpu-proxy" if not args.tpu else "tpu",
        "workload": {"requests": args.requests, "seed": args.seed},
        "tracing_off": off,
        "tracing_on": on,
        "overhead_fraction": overhead,
        "overhead_budget": args.trace_overhead_budget,
    }
    print(json.dumps(results, indent=2))
    with open(args.out, "w") as f:
        json.dump(results, f, indent=2)
    print(f"wrote {args.out}")
    print(f"tracing off: {off['tokens_per_sec']} tok/s   "
          f"on: {on['tokens_per_sec']} tok/s   overhead {overhead:+.1%} "
          f"(budget {args.trace_overhead_budget:.0%})")
    ok = (overhead <= args.trace_overhead_budget
          and off["recompiles_after_warmup"] == 0
          and on["recompiles_after_warmup"] == 0
          and off["greedy_identical_to_sequential"]
          and on["greedy_identical_to_sequential"])
    print("OK" if ok else "FAIL: tracing overhead/recompile/identity broken")
    return 0 if ok else 1


def bench(args):
    if not args.tpu:
        _pin_cpu()
    from deeplearning4j_tpu.zoo.graphs import TransformerEncoder

    model = TransformerEncoder(
        vocab_size=args.vocab, embed_dim=args.embed, n_heads=args.heads,
        n_layers=args.layers, max_len=args.max_len, causal=True,
        lm_head=True, seed=123)
    dec = model.decoder(max_batch=args.max_batch,
                        kv_bucket_min=args.max_len // 4,
                        prompt_bucket_min=8)
    reqs = _workload(args.requests, args.vocab, args.max_len, args.seed)

    # sequential per-request generation: the baseline being replaced,
    # and the distillation corpus for the speculative legs (settle pass
    # + best-of-N, same discipline as the engine legs)
    seq_out = [dec.generate(p, mn, fused_steps=args.fused_steps)
               for p, mn in reqs]
    seq_s = None
    for _ in range(max(1, args.passes)):
        t0 = time.monotonic()
        seq_out = [dec.generate(p, mn, fused_steps=args.fused_steps)
                   for p, mn in reqs]
        dt = time.monotonic() - t0
        seq_s = dt if seq_s is None else min(seq_s, dt)
    seq_tokens = sum(len(o) for o in seq_out)
    print(f"sequential: {round(seq_tokens / seq_s, 1)} tok/s")

    legs = {}
    legs["continuous"] = _run_engine_leg(
        "continuous", model, args, reqs, seq_out)
    if args.paged:
        # same weights (same seed) with use_kernels=True: flash prefill
        # + paged decode attention through the kernel registry, tuned
        # before warmup so the timed passes run the kern:-keyed
        # executables; token identity vs the STOCK sequential reference
        # is part of the leg
        model_k = TransformerEncoder(
            vocab_size=args.vocab, embed_dim=args.embed,
            n_heads=args.heads, n_layers=args.layers,
            max_len=args.max_len, causal=True, lm_head=True, seed=123,
            use_kernels=True)
        legs["paged"] = _run_engine_leg(
            "paged", model_k, args, reqs, seq_out, tune_kernels=True)
    draft = info = None
    if args.speculative:
        if args.smoke:
            draft, info = _oracle_draft(args)
        else:
            seqs = [p + o for (p, _), o in zip(reqs, seq_out)]
            draft, info = _distill_draft(args, seqs, args.distill_epochs)
            print(f"draft distilled: agreement "
                  f"{info['teacher_forced_agreement']} "
                  f"in {info['fit_seconds']}s")
    if args.prefix_cache:
        legs["prefix"] = _run_engine_leg(
            "prefix", model, args, reqs, seq_out, prefix=True)
    if draft is not None:
        legs["speculative"] = _run_engine_leg(
            "speculative", model, args, reqs, seq_out, draft=draft)
    if draft is not None and args.prefix_cache:
        legs["combined"] = _run_engine_leg(
            "combined", model, args, reqs, seq_out, draft=draft,
            prefix=True)

    cont = legs["continuous"]
    results = {
        "bench": "decode_continuous_batching_r02",
        "mode": "cpu-proxy" if not args.tpu else "tpu",
        "model": {"vocab": args.vocab, "embed": args.embed,
                  "heads": args.heads, "layers": args.layers,
                  "max_len": args.max_len},
        "engine": {"max_batch": args.max_batch,
                   "fused_steps": args.fused_steps,
                   "spec_tokens": args.spec_tokens,
                   "prefix_page": args.prefix_page},
        "workload": {"requests": args.requests, "seed": args.seed,
                     "shared_prefix_tokens": max(4, args.max_len // 4),
                     "total_tokens": cont["tokens"],
                     "two_pass": "settle pass untimed, second pass timed"},
        "sequential": {"tokens_per_sec": round(seq_tokens / seq_s, 1),
                       "wall_seconds": round(seq_s, 3),
                       "tokens": seq_tokens},
        "legs": legs,
        "speedup": round(cont["tokens_per_sec"]
                         / (seq_tokens / seq_s), 2),
        "greedy_identical_to_sequential": all(
            leg["greedy_identical_to_sequential"] for leg in legs.values()),
        "recompiles_after_warmup": sum(
            leg["recompiles_after_warmup"] for leg in legs.values()),
    }
    if info is not None:
        results["draft"] = info
    if os.path.exists("BENCH_decode_r01.json"):
        with open("BENCH_decode_r01.json") as f:
            r01 = json.load(f)
        base = r01["continuous"]["tokens_per_sec"]
        results["r01_continuous_baseline_tokens_per_sec"] = base
        if "speculative" in legs:
            results["speculative_vs_r01_baseline"] = round(
                legs["speculative"]["tokens_per_sec"] / base, 2)
            # same-run contrast, stated plainly: at toy scale the draft
            # is only ~2x cheaper per step than the 2-layer target, so
            # speculation's two-launch window need not beat the plain
            # fused window on the CPU proxy (see docs/serving.md)
            results["speculative_vs_continuous_same_run"] = round(
                legs["speculative"]["tokens_per_sec"]
                / cont["tokens_per_sec"], 2)
        if "prefix" in legs and cont["ttft_first_wave_s"] \
                and legs["prefix"]["ttft_first_wave_s"]:
            results["prefix_ttft_cut_vs_cold"] = round(
                1 - legs["prefix"]["ttft_first_wave_s"]["p50"]
                / max(cont["ttft_first_wave_s"]["p50"], 1e-9), 3)
    print(json.dumps(results, indent=2))
    with open(args.out, "w") as f:
        json.dump(results, f, indent=2)
    print(f"wrote {args.out}")
    if args.smoke:
        assert results["greedy_identical_to_sequential"], \
            "a leg's greedy output != sequential reference"
        assert results["recompiles_after_warmup"] == 0, \
            f"{results['recompiles_after_warmup']} recompiles after warmup"
        assert results["speedup"] > 1.0, \
            f"continuous batching slower than sequential " \
            f"(speedup {results['speedup']})"
        if "prefix" in legs:
            assert legs["prefix"]["prefix_cache"]["hits"] > 0, \
                "prefix leg recorded no cache hits"
        if "speculative" in legs:
            acc = legs["speculative"]["speculative"]["acceptance"]
            assert 0.0 < acc <= 1.0, \
                f"speculative leg acceptance not recorded ({acc})"
        if "paged" in legs:
            kinfo = legs["paged"]["kernels"]
            assert kinfo["enabled"] and kinfo["tuned_envelopes"] > 0
            assert "kern:flash_attention:" in kinfo["tag"]
            assert "kern:paged_decode_attention:" in kinfo["tag"]
        print(f"decode-smoke OK: speedup {results['speedup']}x, "
              f"0 recompiles, token-identical"
              + (", prefix hits "
                 f"{legs['prefix']['prefix_cache']['hits']}"
                 if "prefix" in legs else "")
              + (", acceptance "
                 f"{legs['speculative']['speculative']['acceptance']:.2f}"
                 if "speculative" in legs else ""))
    return 0


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--requests", type=int, default=32)
    ap.add_argument("--max-batch", type=int, default=8)
    ap.add_argument("--fused-steps", type=int, default=4)
    ap.add_argument("--vocab", type=int, default=128)
    ap.add_argument("--embed", type=int, default=64)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--max-len", type=int, default=128)
    ap.add_argument("--seed", type=int, default=11)
    ap.add_argument("--out", default="bench_decode.json")
    ap.add_argument("--prefix-cache", action="store_true",
                    help="add the radix prefix-cache leg (+ combined leg "
                         "when --speculative is also set)")
    ap.add_argument("--paged", action="store_true",
                    help="add the use_kernels leg: flash prefill + paged "
                         "decode attention through the kernel registry "
                         "(CPU proxy runs the Pallas interpreter — the "
                         "leg pins identity + zero recompiles, not speed)")
    ap.add_argument("--speculative", action="store_true",
                    help="add the draft-model speculative leg; the draft "
                         "is distilled on the sequential leg's outputs")
    ap.add_argument("--spec-tokens", type=int, default=20,
                    help="draft tokens per speculative window (past "
                         "fused_steps: a window costs ~2 launches "
                         "regardless of K, so deeper drafts amortize)")
    ap.add_argument("--prefix-page", type=int, default=8,
                    help="prefix-cache page size in tokens")
    ap.add_argument("--distill-epochs", type=int, default=1200)
    ap.add_argument("--passes", type=int, default=3,
                    help="timed passes per leg; best is reported and "
                         "every pass recorded")
    ap.add_argument("--traces", action="store_true",
                    help="request-tracing overhead A/B: the continuous "
                         "leg with tracing off then on (sample_every=1), "
                         "plus the trace-derived stage breakdown")
    ap.add_argument("--trace-overhead-budget", type=float, default=0.25,
                    help="with --traces: exit 1 if tracing-on loses more "
                         "than this fraction of tracing-off tokens/s")
    ap.add_argument("--tpu", action="store_true",
                    help="run on the real chip instead of the CPU proxy")
    ap.add_argument("--smoke", action="store_true",
                    help="small workload + assertions (make decode-smoke); "
                         "uses an oracle draft instead of distilling")
    args = ap.parse_args()
    if args.smoke:
        args.requests = min(args.requests, 12)
        args.vocab, args.embed, args.max_len = 32, 16, 48
        args.max_batch = min(args.max_batch, 4)
        args.spec_tokens = min(args.spec_tokens, 6)
        args.prefix_page = 4
        args.passes = 1
    if not args.tpu:
        _pin_cpu()
    if args.traces:
        if args.out == "bench_decode.json":
            args.out = "bench_decode_traces.json"
        return bench_traces(args)
    return bench(args)


if __name__ == "__main__":
    raise SystemExit(main())
