// Native host-side runtime kernels for the TPU framework.
//
// Reference parity: libnd4j's host-side roles that do NOT belong on the TPU —
// threshold/bitmap gradient codecs (libnd4j encodeThreshold/encodeBitmap,
// used by EncodedGradientsAccumulator for compressed gradient messaging),
// DataVec's native ETL (CSV parsing; NativeImageLoader's decode-to-tensor
// role), and batch staging (AffinityManager/MagicQueue feeding replicas).
// On-device work is XLA/Pallas; this library keeps the HOST data path off
// the Python interpreter: OpenMP loops over raw buffers, called via ctypes.
//
// ABI: plain C, int64 sizes, caller-allocated buffers (no allocation across
// the boundary except what the caller owns via numpy).

#include <charconv>
#include <cstdint>
#include <cstring>
#include <cstdlib>
#include <cmath>
#include <vector>

#if defined(_OPENMP)
#include <omp.h>
#endif

extern "C" {

// ---------------------------------------------------------------------------
// Threshold codec (reference: libnd4j TypesConversion/encoder kernels used by
// nd4j "encodeThreshold" op). Encoding: signed 1-based indices, +(i+1) means
// g[i] >= tau (flip +tau), -(i+1) means g[i] <= -tau. Residual handling is
// the caller's job (EncodingHandler semantics).
// ---------------------------------------------------------------------------

int64_t dl4j_encode_threshold(const float* g, int64_t n, float tau,
                              int32_t* out, int64_t cap) {
  int64_t cnt = 0;
  for (int64_t i = 0; i < n; ++i) {
    float v = g[i];
    if (v >= tau) {
      if (cnt < cap) out[cnt] = (int32_t)(i + 1);
      ++cnt;
    } else if (v <= -tau) {
      if (cnt < cap) out[cnt] = -(int32_t)(i + 1);
      ++cnt;
    }
  }
  return cnt;  // may exceed cap: caller re-allocates and retries
}

void dl4j_decode_threshold(const int32_t* enc, int64_t cnt, float tau,
                           float* out) {
  // out is accumulated into (+=), matching the accumulator's "apply the sum
  // of everyone's messages" semantics
  // duplicate indices are legal (a concatenation of several workers'
  // messages), so the accumulation must be atomic
#pragma omp parallel for if (cnt > (1 << 16))
  for (int64_t i = 0; i < cnt; ++i) {
    int32_t e = enc[i];
    if (e > 0) {
#pragma omp atomic
      out[e - 1] += tau;
    } else if (e < 0) {
#pragma omp atomic
      out[-e - 1] -= tau;
    }
  }
}

// Bitmap codec: 2 bits per element (00 none, 01 +tau, 10 -tau), packed into
// uint64 words (reference "encodeBitmap" auto-chosen when >~1/16 dense).
// Returns number of non-zero flips.
int64_t dl4j_encode_bitmap(const float* g, int64_t n, float tau,
                           uint64_t* words) {
  int64_t nwords = (n + 31) / 32;
  int64_t nnz = 0;
#pragma omp parallel for reduction(+ : nnz) if (nwords > (1 << 14))
  for (int64_t w = 0; w < nwords; ++w) {
    uint64_t bits = 0;
    int64_t base = w * 32;
    int64_t end = (base + 32 < n) ? base + 32 : n;
    for (int64_t i = base; i < end; ++i) {
      float v = g[i];
      if (v >= tau) {
        bits |= (uint64_t)1 << ((i - base) * 2);
        ++nnz;
      } else if (v <= -tau) {
        bits |= (uint64_t)2 << ((i - base) * 2);
        ++nnz;
      }
    }
    words[w] = bits;
  }
  return nnz;
}

void dl4j_decode_bitmap(const uint64_t* words, int64_t n, float tau,
                        float* out) {
  int64_t nwords = (n + 31) / 32;
#pragma omp parallel for if (nwords > (1 << 14))
  for (int64_t w = 0; w < nwords; ++w) {
    uint64_t bits = words[w];
    if (!bits) continue;
    int64_t base = w * 32;
    int64_t end = (base + 32 < n) ? base + 32 : n;
    for (int64_t i = base; i < end; ++i) {
      uint64_t s = (bits >> ((i - base) * 2)) & 3;
      if (s == 1)
        out[i] += tau;
      else if (s == 2)
        out[i] -= tau;
    }
  }
}

// ---------------------------------------------------------------------------
// Numeric CSV (reference: DataVec CSVRecordReader's hot path; Java splits
// strings per cell — here one pass indexes lines, OpenMP parses rows).
// ---------------------------------------------------------------------------

static void index_lines(const char* buf, int64_t len,
                        std::vector<int64_t>& starts,
                        std::vector<int64_t>& ends) {
  int64_t i = 0;
  while (i < len) {
    int64_t s = i;
    while (i < len && buf[i] != '\n') ++i;
    int64_t e = i;
    if (e > s && buf[e - 1] == '\r') --e;
    bool blank = true;  // skip empty/whitespace-only lines, matching the
    for (int64_t j = s; j < e; ++j)   // Python fallback's `if r.strip()`
      if (buf[j] != ' ' && buf[j] != '\t') { blank = false; break; }
    if (!blank) {
      starts.push_back(s);
      ends.push_back(e);
    }
    ++i;
  }
}

int64_t dl4j_csv_dims(const char* buf, int64_t len, char delim, int64_t skip,
                      int64_t* rows, int64_t* cols) {
  std::vector<int64_t> starts, ends;
  index_lines(buf, len, starts, ends);
  int64_t nrows = (int64_t)starts.size() - skip;
  if (nrows < 0) nrows = 0;
  *rows = nrows;
  if (nrows == 0) {
    *cols = 0;
    return 0;
  }
  int64_t c = 1;
  for (int64_t i = starts[skip]; i < ends[skip]; ++i)
    if (buf[i] == delim) ++c;
  *cols = c;
  return 0;
}

static inline bool parse_cell(const char* cell, const char* cell_end,
                              float* v) {
  // trim ASCII whitespace on both sides (Python float() semantics), then a
  // BOUNDED locale-free parse that must consume the whole cell
  while (cell < cell_end && (*cell == ' ' || *cell == '\t')) ++cell;
  while (cell_end > cell &&
         (cell_end[-1] == ' ' || cell_end[-1] == '\t'))
    --cell_end;
  if (cell == cell_end) return false;
  // std::from_chars rejects a leading '+'; Python accepts it
  if (*cell == '+') ++cell;
  auto res = std::from_chars(cell, cell_end, *v);
  return res.ec == std::errc() && res.ptr == cell_end;
}

// returns number of parse errors (0 = clean); a row with a cell count
// different from `cols` counts as an error (the Python fallback raises)
int64_t dl4j_parse_csv(const char* buf, int64_t len, char delim, int64_t skip,
                       float* out, int64_t rows, int64_t cols) {
  std::vector<int64_t> starts, ends;
  index_lines(buf, len, starts, ends);
  int64_t avail = (int64_t)starts.size() - skip;
  int64_t n = avail < rows ? avail : rows;
  int64_t errors = 0;
#pragma omp parallel for reduction(+ : errors) if (n > 256)
  for (int64_t r = 0; r < n; ++r) {
    const char* p = buf + starts[r + skip];
    const char* lineend = buf + ends[r + skip];
    float* dst = out + r * cols;
    for (int64_t c = 0; c < cols; ++c) {
      if (p > lineend) {  // row ran out of cells
        ++errors;
        dst[c] = 0.0f;
        continue;
      }
      const char* cell_end = p;
      while (cell_end < lineend && *cell_end != delim) ++cell_end;
      float v = 0.0f;
      if (!parse_cell(p, cell_end, &v)) {
        ++errors;
        v = 0.0f;
      }
      dst[c] = v;
      p = cell_end + 1;  // past the delimiter (or past lineend = row done)
    }
    if (p <= lineend) ++errors;  // extra cells beyond `cols`
  }
  return errors;
}

// ---------------------------------------------------------------------------
// Pixel/ubyte conversion (reference: NativeImageLoader's decode+normalize
// into a float tensor) and batch staging gather (reference: MagicQueue
// assembling per-worker minibatches).
// ---------------------------------------------------------------------------

void dl4j_u8_to_f32(const uint8_t* src, int64_t n, float scale, float shift,
                    float* dst) {
#pragma omp parallel for if (n > (1 << 18))
  for (int64_t i = 0; i < n; ++i) dst[i] = (float)src[i] * scale + shift;
}

void dl4j_gather_rows(const char* src, const int64_t* idx, int64_t nidx,
                      int64_t row_bytes, char* dst) {
#pragma omp parallel for if (nidx * row_bytes > (1 << 20))
  for (int64_t i = 0; i < nidx; ++i)
    memcpy(dst + i * row_bytes, src + idx[i] * row_bytes, (size_t)row_bytes);
}

// ---------------------------------------------------------------------------
// Word2Vec skip-gram pair generation (reference: the nd4j SkipGram native op
// builds (center, context) pairs on the native side; word2vec.c dynamic
// windows). Sentences arrive concatenated with an offsets array.
// out: int32 pairs [cap][2]; returns pair count (<= cap guaranteed by the
// caller sizing cap = total_tokens * 2 * window).
// ---------------------------------------------------------------------------

static inline uint64_t xorshift64(uint64_t* s) {
  uint64_t x = *s;
  x ^= x << 13;
  x ^= x >> 7;
  x ^= x << 17;
  return *s = x;
}

// io_state: in = RNG state to start from (0 maps to the init constant);
// out = state after the walk, so chunked callers can continue the stream
// without replaying draws host-side.
int64_t dl4j_w2v_pairs(const int32_t* tokens, const int64_t* offsets,
                       int64_t n_sentences, int64_t window,
                       uint64_t* io_state, int32_t* out, int64_t cap) {
  if (window < 1) return -1;  // caller raises; avoids modulo-by-zero
  int64_t cnt = 0;
  uint64_t st = *io_state ? *io_state : 0x9E3779B97F4A7C15ull;
  for (int64_t si = 0; si < n_sentences; ++si) {
    const int32_t* sent = tokens + offsets[si];
    int64_t n = offsets[si + 1] - offsets[si];
    if (n < 2) continue;
    for (int64_t i = 0; i < n; ++i) {
      int64_t b = 1 + (int64_t)(xorshift64(&st) % (uint64_t)window);
      int64_t lo = i - b < 0 ? 0 : i - b;
      int64_t hi = i + b + 1 > n ? n : i + b + 1;
      for (int64_t j = lo; j < hi; ++j) {
        if (j == i) continue;
        if (cnt < cap) {
          out[cnt * 2] = sent[i];
          out[cnt * 2 + 1] = sent[j];
        }
        ++cnt;
      }
    }
  }
  *io_state = st;
  return cnt;
}

int dl4j_native_version() { return 2; }

int dl4j_native_threads() {
#if defined(_OPENMP)
  return omp_get_max_threads();
#else
  return 1;
#endif
}

}  // extern "C"
