"""Closed-loop multi-client serving benchmark (ISSUE 5 acceptance): N
concurrent clients, each looping submit -> wait -> submit against

  locked   — the pre-round-9 baseline: one global lock, one exact-shape
             forward per request (``InferenceServer`` with
             ``batching=None``), and
  batched  — the dynamic micro-batching engine: shared padded launches,
             power-of-two buckets, zero recompiles after ``warmup()``
             (``parallel.batcher.InferenceEngine``).

Reports req/s, rows/s, latency p50/p95/p99, engine fill ratio, and the
speedup; writes ``bench_serving.json``. The acceptance bar is >= 4x
throughput at 8 clients on the CPU proxy.

Runs on CPU by default (``--tpu`` opts into the real chip): a serving
bench must not contend with the box's single axon TPU tunnel.

``--smoke`` is the ``make serve-smoke`` path: start a real HTTP
``InferenceServer``, fire concurrent ``/predict`` clients, scrape
``/metrics``, stop cleanly, assert the engine never recompiled.
"""

import argparse
import json
import os
import sys
import threading
import time


def _pin_cpu():
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        # 8 virtual devices: the ParallelInference-backed deployment (the
        # default --backend) shards launches the way a TPU pod slice does
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        from jax._src import xla_bridge as _xb

        _xb._backend_factories.pop("axon", None)
    except Exception:
        pass


def _build_net(n_in, hidden, n_out, seed=0):
    import numpy as np

    from deeplearning4j_tpu.conf import Activation, InputType, WeightInit
    from deeplearning4j_tpu.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.conf.losses import LossMCXENT
    from deeplearning4j_tpu.conf.multilayer import NeuralNetConfiguration
    from deeplearning4j_tpu.conf.updaters import Sgd
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    conf = (NeuralNetConfiguration.builder().seed(seed)
            .updater(Sgd(0.1)).weight_init(WeightInit.XAVIER).list()
            .layer(DenseLayer(n_out=hidden, activation=Activation.RELU))
            .layer(DenseLayer(n_out=hidden, activation=Activation.RELU))
            .layer(OutputLayer(n_out=n_out, activation=Activation.SOFTMAX,
                               loss_fn=LossMCXENT()))
            .set_input_type(InputType.feed_forward(n_in)).build())
    net = MultiLayerNetwork(conf).init()
    # one throwaway fit step so serving hits a realistic trained model
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(32, n_in)).astype(np.float32)
    y = np.eye(n_out, dtype=np.float32)[rng.integers(0, n_out, 32)]
    net.fit(x, y)
    return net


def _quantiles(sorted_ms):
    def q(p):
        if not sorted_ms:
            return 0.0
        i = min(int(p * len(sorted_ms)), len(sorted_ms) - 1)
        return sorted_ms[i]

    return {"p50_ms": round(q(0.50), 3), "p95_ms": round(q(0.95), 3),
            "p99_ms": round(q(0.99), 3)}


def _closed_loop(predict, clients, seconds, sizes, n_in):
    """``clients`` threads loop predict(x) for ``seconds``; returns
    (requests, rows, sorted per-request latencies ms)."""
    import numpy as np

    stop = threading.Event()
    lat = [[] for _ in range(clients)]
    rows = [0] * clients

    def run(ci):
        rng = np.random.default_rng(ci)
        payloads = [rng.normal(size=(s, n_in)).astype(np.float32)
                    for s in sizes]
        i = 0
        while not stop.is_set():
            x = payloads[i % len(payloads)]
            t0 = time.perf_counter()
            predict(x)
            lat[ci].append((time.perf_counter() - t0) * 1000.0)
            rows[ci] += x.shape[0]
            i += 1

    threads = [threading.Thread(target=run, args=(ci,))
               for ci in range(clients)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    time.sleep(seconds)
    stop.set()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    flat = sorted(ms for per in lat for ms in per)
    return len(flat), sum(rows), flat, wall


def bench(args):
    import numpy as np

    from deeplearning4j_tpu import telemetry
    from deeplearning4j_tpu.optimize import aot_cache
    from deeplearning4j_tpu.parallel.batcher import (
        BatchingConfig,
        InferenceEngine,
    )

    net = _build_net(args.n_in, args.hidden, args.n_out)
    sizes = tuple(int(s) for s in args.sizes.split(","))
    results = {"clients": args.clients, "seconds": args.seconds,
               "sizes": list(sizes), "backend": args.backend,
               "model": f"mlp {args.n_in}-{args.hidden}x2-{args.n_out}"}

    if args.backend == "pi":
        # the deployment the ISSUE targets: serving behind a sharded
        # ParallelInference, where EVERY launch pays multi-device dispatch
        # — the cost dynamic batching exists to amortize (on this CPU
        # proxy a 1-row sharded launch costs the same ~2 ms as a 32-row
        # one; a TPU pod slice behaves the same way)
        from deeplearning4j_tpu.parallel import ParallelInference

        baseline_model = ParallelInference(net, bucketize=False)  # old pad
        engine_model = ParallelInference(net)
    else:
        baseline_model = engine_model = net

    # --- locked baseline: global lock, one request per launch -------------
    lock = threading.Lock()

    def locked_predict(x):
        # host materialization included — the engine demux pays it too
        with lock:
            return np.asarray(baseline_model.output(x))

    def measure(predict):
        """Best round by req/s: the box is shared, a slow round means
        background contention, not a slower serving path."""
        best = None
        for _ in range(max(args.rounds, 1)):
            n_req, n_rows, lat, wall = _closed_loop(
                predict, args.clients, args.seconds, sizes, args.n_in)
            cur = {"req_per_s": round(n_req / wall, 1),
                   "rows_per_s": round(n_rows / wall, 1),
                   **_quantiles(lat)}
            if best is None or cur["req_per_s"] > best["req_per_s"]:
                best = cur
        return best

    for s in sizes:  # prime every request shape out of the measurement
        locked_predict(np.zeros((s, args.n_in), np.float32))
    results["locked"] = measure(locked_predict)

    # --- batched engine ---------------------------------------------------
    eng = InferenceEngine(engine_model, BatchingConfig(
        max_batch=args.max_batch, max_delay_ms=args.max_delay_ms,
        settle_ms=args.settle_ms),
        graph_opt=not args.no_graph_opt and args.backend != "pi")
    warm = eng.warmup()
    miss0 = aot_cache.stats()["misses"]
    results["batched"] = measure(eng.predict)
    recompiles = aot_cache.stats()["misses"] - miss0
    snap = telemetry.REGISTRY.snapshot(run_collectors=False)
    fill = snap.get("dl4j_serving_batch_fill_ratio", {})
    per_batch = snap.get("dl4j_serving_batch_requests", {})
    results["batched"].update({
        "warmup": warm,
        "recompiles_after_warmup": recompiles,
        "mean_fill_ratio": round(fill.get("mean", 0.0), 3),
        "mean_requests_per_launch": round(per_batch.get("mean", 0.0), 2),
    })
    eng.close()

    results["speedup"] = round(
        results["batched"]["req_per_s"]
        / max(results["locked"]["req_per_s"], 1e-9), 2)

    print(json.dumps(results, indent=2))
    with open(args.out, "w") as f:
        json.dump(results, f, indent=2)
    print(f"\nlocked  : {results['locked']['req_per_s']:>9} req/s   "
          f"p95 {results['locked']['p95_ms']} ms")
    print(f"batched : {results['batched']['req_per_s']:>9} req/s   "
          f"p95 {results['batched']['p95_ms']} ms")
    print(f"speedup : {results['speedup']}x   "
          f"(recompiles after warmup: {recompiles})")
    if args.assert_speedup and results["speedup"] < args.assert_speedup:
        print(f"FAIL: speedup {results['speedup']} < {args.assert_speedup}")
        return 1
    return 0


def bench_multi_model(args):
    """``--multi-model``: the two-tenant isolation A/B (ISSUE 13). One
    healthy tenant and one tenant whose CANARY version is degraded by a
    seeded fault plan serve concurrent closed-loop traffic on one
    platform host. Reports per-tenant req/s, latency quantiles, shed
    counts, the automatic-rollback record, and the two isolation
    invariants: the healthy tenant's responses stay byte-identical and
    the host performs ZERO recompiles after warmup while the canary
    trips, sheds, and rolls back. ``--assert-isolation`` exits 1 if
    either invariant breaks or the gate never trips."""
    import tempfile

    import numpy as np

    from deeplearning4j_tpu.optimize import aot_cache
    from deeplearning4j_tpu.parallel.batcher import BatchingConfig
    from deeplearning4j_tpu.parallel.platform import (
        CanaryGate,
        ModelPlatform,
        ModelRegistry,
        TenantConfig,
    )
    from deeplearning4j_tpu.resilience import FaultPlan
    from deeplearning4j_tpu.telemetry import REGISTRY

    net_a = _build_net(args.n_in, args.hidden, args.n_out, seed=1)
    net_b = _build_net(args.n_in, args.hidden + 32, args.n_out, seed=2)
    # v2 = same conf, "newly trained" weights (the real rollout shape:
    # same conf-derived AOT graph key, so the canary warms for free)
    net_b2 = type(net_b)(net_b.conf).init()
    net_b2.set_params_flat(np.asarray(net_b.params_flat()) + 0.05)

    reg = ModelRegistry(tempfile.mkdtemp(prefix="dl4j_mt_bench_"))
    reg.publish("tenant_a", net_a)
    reg.publish("tenant_b", net_b)
    reg.publish("tenant_b", net_b2)
    plat = ModelPlatform(reg, seed=7)
    cfg = TenantConfig(batching=BatchingConfig(
        max_batch=args.max_batch, max_delay_ms=args.max_delay_ms,
        settle_ms=args.settle_ms))
    plat.deploy("tenant_a", config=cfg)
    plat.deploy("tenant_b", version=1, config=cfg)

    probe = np.zeros((2, args.n_in), np.float32)
    y_a0 = np.asarray(plat.predict("tenant_a", probe)).tobytes()
    plat.deploy_canary("tenant_b", 2, fraction=0.5,
                       gate=CanaryGate(max_consecutive_failures=5))
    miss0 = aot_cache.stats()["misses"]
    req0 = {
        k: v for k, v in REGISTRY.snapshot(run_collectors=False).items()
        if k.startswith("dl4j_serving_requests_total")}

    stop = threading.Event()
    per_tenant = {"tenant_a": {"lat": [], "ok": 0, "failed": 0},
                  "tenant_b": {"lat": [], "ok": 0, "failed": 0}}
    healthy_identical = [True]

    def client(tenant, ci):
        import numpy as _np

        rng = _np.random.default_rng(ci)
        rec = per_tenant[tenant]
        payloads = [rng.normal(size=(s, args.n_in)).astype(_np.float32)
                    for s in (1, 2, 3, 4)]
        i = 0
        while not stop.is_set():
            x = payloads[i % 4]
            t0 = time.perf_counter()
            try:
                plat.predict(tenant, x)
                rec["lat"].append((time.perf_counter() - t0) * 1000.0)
                rec["ok"] += 1
            except Exception:
                rec["failed"] += 1
            i += 1

    def probe_healthy():
        # the byte-identity monitor rides WITH the chaos, not after it
        while not stop.is_set():
            y = np.asarray(plat.predict("tenant_a", probe)).tobytes()
            if y != y_a0:
                healthy_identical[0] = False
            time.sleep(0.01)

    plan = FaultPlan(seed=11).inject("serving.launch:tenant_b#canary")
    half = max(args.clients // 2, 1)
    threads = ([threading.Thread(target=client, args=("tenant_a", ci))
                for ci in range(half)]
               + [threading.Thread(target=client, args=("tenant_b", ci))
                  for ci in range(half)]
               + [threading.Thread(target=probe_healthy)])
    with plan.armed():
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        time.sleep(args.seconds)
        stop.set()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0

    recompiles = aot_cache.stats()["misses"] - miss0
    post = np.asarray(plat.predict("tenant_b", probe)).tobytes()
    y_b_v1 = np.asarray(net_b.output(probe)).tobytes()
    st_b = plat.stats()["tenant_b"]
    rollback = st_b.get("last_rollback")
    req1 = {
        k: v for k, v in REGISTRY.snapshot(run_collectors=False).items()
        if k.startswith("dl4j_serving_requests_total")}
    sheds = {k: req1[k] - req0.get(k, 0) for k in req1
             if '"shed"' in k or '"error"' in k or '"rejected"' in k}
    plat.close()

    results = {"mode": "multi-model", "clients": args.clients,
               "seconds": args.seconds, "wall": round(wall, 2),
               "fault_plan": "seed=11 serving.launch:tenant_b#canary",
               "platform_seed": 7}
    for name, rec in per_tenant.items():
        lat = sorted(rec["lat"])
        results[name] = {
            "req_per_s": round(len(lat) / wall, 1),
            "ok": rec["ok"], "failed": rec["failed"],
            **_quantiles(lat)}
    results["tenant_b"]["rollback"] = rollback
    results["shed_error_counts"] = {
        k.split("{", 1)[1].rstrip("}"): v for k, v in sorted(sheds.items())
        if v}
    results["recompiles_after_warmup"] = recompiles
    results["healthy_tenant_bytes_identical"] = healthy_identical[0]
    results["incumbent_restored_after_rollback"] = post == y_b_v1

    print(json.dumps(results, indent=2))
    with open(args.out, "w") as f:
        json.dump(results, f, indent=2)
    ra, rb = results["tenant_a"], results["tenant_b"]
    print(f"\ntenant_a (healthy): {ra['req_per_s']:>8} req/s  "
          f"p95 {ra['p95_ms']} ms  failed {ra['failed']}")
    print(f"tenant_b (canary) : {rb['req_per_s']:>8} req/s  "
          f"p95 {rb['p95_ms']} ms  failed {rb['failed']}")
    print(f"rollback: {rollback and rollback['reason']!r} "
          f"@ request {rollback and rollback['at_request']}   "
          f"recompiles {recompiles}   "
          f"healthy identical {healthy_identical[0]}")
    if args.assert_isolation:
        ok = (recompiles == 0 and healthy_identical[0]
              and rollback is not None
              and results["incumbent_restored_after_rollback"]
              and ra["failed"] == 0)
        print("OK" if ok else "FAIL: isolation invariant broken")
        return 0 if ok else 1
    return 0


def bench_traces(args):
    """``--traces``: request-tracing overhead A/B on the batched engine.
    The same closed-loop traffic runs twice — tracing OFF (the module
    flag short-circuits ``start_trace`` to one boolean check) then ON
    (every request carries a span through queued → admitted → grouped →
    launched → demuxed) — and the JSON carries both throughputs, the
    overhead fraction against ``--trace-overhead-budget``, the
    trace-derived queue-wait / batch-wait / launch breakdown, and the
    zero-recompile check for BOTH modes (tracing is host-side
    monotonic_ns + list appends; it must never mint an AOT key)."""
    from deeplearning4j_tpu.optimize import aot_cache
    from deeplearning4j_tpu.parallel.batcher import (
        BatchingConfig,
        InferenceEngine,
    )
    from deeplearning4j_tpu.telemetry import tracing

    net = _build_net(args.n_in, args.hidden, args.n_out)
    sizes = tuple(int(s) for s in args.sizes.split(","))
    eng = InferenceEngine(net, BatchingConfig(
        max_batch=args.max_batch, max_delay_ms=args.max_delay_ms,
        settle_ms=args.settle_ms), graph_opt=not args.no_graph_opt)
    eng.warmup()

    def measure():
        best = None
        for _ in range(max(args.rounds, 1)):
            n_req, _rows, lat, wall = _closed_loop(
                eng.predict, args.clients, args.seconds, sizes, args.n_in)
            cur = {"req_per_s": round(n_req / wall, 1), **_quantiles(lat)}
            if best is None or cur["req_per_s"] > best["req_per_s"]:
                best = cur
        return best

    results = {"mode": "traces", "clients": args.clients,
               "seconds": args.seconds, "rounds": args.rounds,
               "sizes": list(sizes)}
    tracing.disable()
    miss0 = aot_cache.stats()["misses"]
    results["tracing_off"] = measure()
    results["tracing_off"]["recompiles_after_warmup"] = (
        aot_cache.stats()["misses"] - miss0)
    tracing.enable(seed=7, sample_every=64)
    miss1 = aot_cache.stats()["misses"]
    results["tracing_on"] = measure()
    results["tracing_on"]["recompiles_after_warmup"] = (
        aot_cache.stats()["misses"] - miss1)
    results["tracing_on"]["sampler"] = tracing.stats()
    bd = tracing.stage_breakdown()
    results["tracing_on"]["stage_breakdown"] = {
        k: v for k, v in bd.items() if v is not None}
    tracing.disable()
    eng.close()

    off = results["tracing_off"]["req_per_s"]
    on = results["tracing_on"]["req_per_s"]
    overhead = round(1.0 - on / max(off, 1e-9), 4)
    results["overhead_fraction"] = overhead
    results["overhead_budget"] = args.trace_overhead_budget

    print(json.dumps(results, indent=2))
    with open(args.out, "w") as f:
        json.dump(results, f, indent=2)
    print(f"\ntracing off: {off:>9} req/s   on: {on:>9} req/s   "
          f"overhead {overhead:+.1%} (budget {args.trace_overhead_budget:.0%})")
    ok = (overhead <= args.trace_overhead_budget
          and results["tracing_off"]["recompiles_after_warmup"] == 0
          and results["tracing_on"]["recompiles_after_warmup"] == 0)
    print("OK" if ok else "FAIL: tracing overhead/recompile budget broken")
    return 0 if ok else 1


def bench_quant(args):
    """``--quant``: the f32-vs-int8 quantized-serving A/B (ISSUE 20).
    Two fresh platforms serve the same closed-loop traffic:

      f32   — publish v1, deploy, measure.
      int8  — publish v1, calibrate + quantize -> publish v2, deploy v1,
              ``deploy_canary`` v2 behind an accuracy-armed gate, drive
              canary traffic, ``promote`` (which pre-warms the quantized
              executables), then measure the promoted quantized serving.

    Reports per-mode req/s, latency quantiles and
    recompiles-after-warmup (asserted ZERO for both — the quantized
    version must be fully warmed at promote time, not on first
    traffic), plus the canary's observed ``accuracy_max_delta``.

    Honest caveat baked into the JSON: on the CPU proxy XLA often runs
    int8 dot products SLOWER than f32 (no VNNI path through this
    emitter), so the ratio here validates the plumbing + accuracy, not
    the TPU speedup — that A/B is one ``--tpu`` run away.
    """
    import tempfile

    import numpy as np

    from deeplearning4j_tpu.nn import inference_opt as iopt
    from deeplearning4j_tpu.optimize import aot_cache
    from deeplearning4j_tpu.parallel.batcher import BatchingConfig
    from deeplearning4j_tpu.parallel.platform import (
        CanaryGate,
        ModelPlatform,
        ModelRegistry,
        TenantConfig,
    )

    sizes = tuple(int(s) for s in args.sizes.split(","))
    cfg = TenantConfig(batching=BatchingConfig(
        max_batch=args.max_batch, max_delay_ms=args.max_delay_ms,
        settle_ms=args.settle_ms))
    results = {"mode": "quant", "clients": args.clients,
               "seconds": args.seconds, "sizes": list(sizes),
               "n_in": args.n_in, "hidden": args.hidden,
               "platform_seed": 7,
               "cpu_proxy_note": (
                   "CPU XLA int8 dot is often slower than f32 (no VNNI "
                   "path); this leg validates plumbing + accuracy, the "
                   "TPU speed A/B is one --tpu run away")}

    def measure(predict):
        best = None
        for _ in range(max(args.rounds, 1)):
            n_req, _rows, lat, wall = _closed_loop(
                predict, args.clients, args.seconds, sizes, args.n_in)
            cur = {"req_per_s": round(n_req / wall, 1), **_quantiles(lat)}
            if best is None or cur["req_per_s"] > best["req_per_s"]:
                best = cur
        return best

    # ---- mode 1: f32 incumbent on a fresh platform -----------------------
    net = _build_net(args.n_in, args.hidden, args.n_out, seed=1)
    reg = ModelRegistry(tempfile.mkdtemp(prefix="dl4j_quant_bench_"))
    reg.publish("m", net)
    plat = ModelPlatform(reg, seed=7)
    plat.deploy("m", version=1, config=cfg)
    miss0 = aot_cache.stats()["misses"]
    results["f32"] = measure(lambda x: plat.predict("m", x))
    results["f32"]["recompiles_after_warmup"] = (
        aot_cache.stats()["misses"] - miss0)
    plat.close()

    # ---- mode 2: int8 canary -> promote on a fresh platform --------------
    rng = np.random.default_rng(0)
    cal_batches = [rng.normal(size=(32, args.n_in)).astype(np.float32)
                   for _ in range(4)]
    rec = iopt.calibrate(net, cal_batches)
    qnet = iopt.quantize_for_inference(net, rec)
    plat2 = ModelPlatform(reg, seed=7)
    plat2.deploy("m", version=1, config=cfg)
    reg.publish("m", qnet)
    plat2.deploy_canary("m", version=2, fraction=0.5,
                        gate=CanaryGate(min_requests=8,
                                        max_accuracy_delta=0.25,
                                        accuracy_sample=1.0))
    miss_canary = aot_cache.stats()["misses"]
    for i in range(24):
        x = np.random.default_rng(100 + i).normal(
            size=(sizes[i % len(sizes)], args.n_in)).astype(np.float32)
        plat2.predict("m", x)
    canary_recompiles = aot_cache.stats()["misses"] - miss_canary
    canary = plat2.stats()["m"].get("canary") or {}
    promoted = plat2.promote("m")
    miss1 = aot_cache.stats()["misses"]
    results["int8"] = measure(lambda x: plat2.predict("m", x))
    results["int8"]["recompiles_after_warmup"] = (
        aot_cache.stats()["misses"] - miss1)
    results["int8"]["canary_recompiles"] = canary_recompiles
    results["int8"]["promoted_version"] = promoted["version"]
    results["accuracy_max_delta"] = canary.get("accuracy_max_delta")
    results["accuracy_samples"] = canary.get("accuracy_samples")
    results["quantization"] = {"scheme": rec.scheme,
                               "calibration_digest": rec.digest[:8]}
    plat2.close()

    speed = round(results["int8"]["req_per_s"]
                  / max(results["f32"]["req_per_s"], 1e-9), 3)
    results["int8_over_f32"] = speed

    print(json.dumps(results, indent=2))
    with open(args.out, "w") as f:
        json.dump(results, f, indent=2)
    rf, rq = results["f32"], results["int8"]
    print(f"\nf32 : {rf['req_per_s']:>8} req/s  p95 {rf['p95_ms']} ms  "
          f"recompiles {rf['recompiles_after_warmup']}")
    print(f"int8: {rq['req_per_s']:>8} req/s  p95 {rq['p95_ms']} ms  "
          f"recompiles {rq['recompiles_after_warmup']}  "
          f"(canary {canary_recompiles})")
    print(f"accuracy_max_delta {results['accuracy_max_delta']} over "
          f"{results['accuracy_samples']} samples   "
          f"int8/f32 {speed}x (CPU proxy)")
    ok = (rf["recompiles_after_warmup"] == 0
          and rq["recompiles_after_warmup"] == 0
          and canary_recompiles == 0
          and promoted["version"] == 2
          and results["accuracy_max_delta"] is not None
          and results["accuracy_max_delta"] <= 0.25)
    print("OK" if ok else "FAIL: quantized-serving invariant broken")
    return 0 if ok else 1


def smoke(args):
    """make serve-smoke: HTTP server up -> concurrent predicts ->
    /metrics scrape -> clean stop."""
    import urllib.request

    import numpy as np

    from deeplearning4j_tpu.optimize import aot_cache
    from deeplearning4j_tpu.parallel.batcher import BatchingConfig
    from deeplearning4j_tpu.parallel.serving import InferenceServer

    net = _build_net(args.n_in, args.hidden, args.n_out)
    server = InferenceServer(net, batching=BatchingConfig(
        max_batch=args.max_batch, max_delay_ms=args.max_delay_ms)
    ).start(port=0, warmup=True)
    base = f"http://127.0.0.1:{server.port}"
    miss0 = aot_cache.stats()["misses"]
    errors = []

    def client(ci):
        rng = np.random.default_rng(ci)
        for i in range(8):
            n = 1 + (ci + i) % 5
            x = rng.normal(size=(n, args.n_in)).astype(np.float32)
            req = urllib.request.Request(
                base + "/predict",
                json.dumps({"inputs": [x.tolist()]}).encode(),
                {"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=30) as r:
                body = json.loads(r.read())
            if len(body["outputs"][0]) != n:
                errors.append(f"client {ci}: demux row count mismatch")

    threads = [threading.Thread(target=client, args=(ci,))
               for ci in range(args.clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    text = urllib.request.urlopen(base + "/metrics",
                                  timeout=10).read().decode()
    server.stop()
    recompiles = aot_cache.stats()["misses"] - miss0
    ok = (not errors and recompiles == 0
          and "dl4j_serving_requests_total" in text
          and "dl4j_serving_batches_total" in text)
    print(f"serve-smoke: {args.clients} clients x 8 ragged predicts, "
          f"recompiles={recompiles}, errors={errors or 'none'}, "
          f"metrics={'ok' if 'dl4j_serving' in text else 'MISSING'}")
    print("OK" if ok else "FAIL")
    return 0 if ok else 1


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--clients", type=int, default=8)
    ap.add_argument("--seconds", type=float, default=3.0)
    ap.add_argument("--rounds", type=int, default=3,
                    help="measurement rounds per mode; best req/s wins")
    ap.add_argument("--sizes", default="1,2,3,4",
                    help="comma list of request row counts cycled per client")
    ap.add_argument("--max-batch", type=int, default=32)
    ap.add_argument("--max-delay-ms", type=float, default=2.0)
    ap.add_argument("--settle-ms", type=float, default=0.2)
    ap.add_argument("--backend", choices=("pi", "single"), default="pi",
                    help="pi = sharded ParallelInference deployment "
                         "(default), single = bare network")
    ap.add_argument("--n-in", type=int, default=64)
    ap.add_argument("--hidden", type=int, default=256)
    ap.add_argument("--n-out", type=int, default=10)
    ap.add_argument("--no-graph-opt", action="store_true")
    ap.add_argument("--out", default="bench_serving.json")
    ap.add_argument("--assert-speedup", type=float, default=0.0,
                    help="exit 1 if batched/locked speedup is below this")
    ap.add_argument("--smoke", action="store_true",
                    help="HTTP round-trip smoke instead of the benchmark")
    ap.add_argument("--multi-model", action="store_true",
                    help="two-tenant platform isolation A/B: healthy "
                         "tenant + fault-injected canary, per-tenant "
                         "req/s / p95 / sheds / rollback / recompiles")
    ap.add_argument("--assert-isolation", action="store_true",
                    help="with --multi-model: exit 1 unless the healthy "
                         "tenant stayed byte-identical with zero "
                         "recompiles and the canary rolled back")
    ap.add_argument("--traces", action="store_true",
                    help="request-tracing overhead A/B: the same "
                         "closed-loop traffic with tracing off then on, "
                         "plus the trace-derived stage breakdown")
    ap.add_argument("--trace-overhead-budget", type=float, default=0.25,
                    help="with --traces: exit 1 if tracing-on loses more "
                         "than this fraction of tracing-off req/s")
    ap.add_argument("--quant", action="store_true",
                    help="f32-vs-int8 quantized serving A/B: calibrate, "
                         "quantize, canary with the accuracy gate, "
                         "promote, measure — zero recompiles both modes")
    ap.add_argument("--tpu", action="store_true",
                    help="run on the real accelerator (default: CPU pin)")
    args = ap.parse_args()
    if not args.tpu:
        _pin_cpu()
    if args.multi_model:
        if args.out == "bench_serving.json":
            args.out = "bench_serving_mt.json"
        return bench_multi_model(args)
    if args.traces:
        if args.out == "bench_serving.json":
            args.out = "bench_serving_traces.json"
        return bench_traces(args)
    if args.quant:
        if args.out == "bench_serving.json":
            args.out = "bench_serving_quant.json"
        return bench_quant(args)
    return smoke(args) if args.smoke else bench(args)


if __name__ == "__main__":
    sys.exit(main())
