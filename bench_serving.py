"""Closed-loop multi-client serving benchmark (ISSUE 5 acceptance): N
concurrent clients, each looping submit -> wait -> submit against

  locked   — the pre-round-9 baseline: one global lock, one exact-shape
             forward per request (``InferenceServer`` with
             ``batching=None``), and
  batched  — the dynamic micro-batching engine: shared padded launches,
             power-of-two buckets, zero recompiles after ``warmup()``
             (``parallel.batcher.InferenceEngine``).

Reports req/s, rows/s, latency p50/p95/p99, engine fill ratio, and the
speedup; writes ``bench_serving.json``. The acceptance bar is >= 4x
throughput at 8 clients on the CPU proxy.

Runs on CPU by default (``--tpu`` opts into the real chip): a serving
bench must not contend with the box's single axon TPU tunnel.

``--smoke`` is the ``make serve-smoke`` path: start a real HTTP
``InferenceServer``, fire concurrent ``/predict`` clients, scrape
``/metrics``, stop cleanly, assert the engine never recompiled.
"""

import argparse
import json
import os
import sys
import threading
import time


def _pin_cpu():
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        # 8 virtual devices: the ParallelInference-backed deployment (the
        # default --backend) shards launches the way a TPU pod slice does
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        from jax._src import xla_bridge as _xb

        _xb._backend_factories.pop("axon", None)
    except Exception:
        pass


def _build_net(n_in, hidden, n_out, seed=0):
    import numpy as np

    from deeplearning4j_tpu.conf import Activation, InputType, WeightInit
    from deeplearning4j_tpu.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.conf.losses import LossMCXENT
    from deeplearning4j_tpu.conf.multilayer import NeuralNetConfiguration
    from deeplearning4j_tpu.conf.updaters import Sgd
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    conf = (NeuralNetConfiguration.builder().seed(seed)
            .updater(Sgd(0.1)).weight_init(WeightInit.XAVIER).list()
            .layer(DenseLayer(n_out=hidden, activation=Activation.RELU))
            .layer(DenseLayer(n_out=hidden, activation=Activation.RELU))
            .layer(OutputLayer(n_out=n_out, activation=Activation.SOFTMAX,
                               loss_fn=LossMCXENT()))
            .set_input_type(InputType.feed_forward(n_in)).build())
    net = MultiLayerNetwork(conf).init()
    # one throwaway fit step so serving hits a realistic trained model
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(32, n_in)).astype(np.float32)
    y = np.eye(n_out, dtype=np.float32)[rng.integers(0, n_out, 32)]
    net.fit(x, y)
    return net


def _quantiles(sorted_ms):
    def q(p):
        if not sorted_ms:
            return 0.0
        i = min(int(p * len(sorted_ms)), len(sorted_ms) - 1)
        return sorted_ms[i]

    return {"p50_ms": round(q(0.50), 3), "p95_ms": round(q(0.95), 3),
            "p99_ms": round(q(0.99), 3)}


def _closed_loop(predict, clients, seconds, sizes, n_in):
    """``clients`` threads loop predict(x) for ``seconds``; returns
    (requests, rows, sorted per-request latencies ms)."""
    import numpy as np

    stop = threading.Event()
    lat = [[] for _ in range(clients)]
    rows = [0] * clients

    def run(ci):
        rng = np.random.default_rng(ci)
        payloads = [rng.normal(size=(s, n_in)).astype(np.float32)
                    for s in sizes]
        i = 0
        while not stop.is_set():
            x = payloads[i % len(payloads)]
            t0 = time.perf_counter()
            predict(x)
            lat[ci].append((time.perf_counter() - t0) * 1000.0)
            rows[ci] += x.shape[0]
            i += 1

    threads = [threading.Thread(target=run, args=(ci,))
               for ci in range(clients)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    time.sleep(seconds)
    stop.set()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    flat = sorted(ms for per in lat for ms in per)
    return len(flat), sum(rows), flat, wall


def bench(args):
    import numpy as np

    from deeplearning4j_tpu import telemetry
    from deeplearning4j_tpu.optimize import aot_cache
    from deeplearning4j_tpu.parallel.batcher import (
        BatchingConfig,
        InferenceEngine,
    )

    net = _build_net(args.n_in, args.hidden, args.n_out)
    sizes = tuple(int(s) for s in args.sizes.split(","))
    results = {"clients": args.clients, "seconds": args.seconds,
               "sizes": list(sizes), "backend": args.backend,
               "model": f"mlp {args.n_in}-{args.hidden}x2-{args.n_out}"}

    if args.backend == "pi":
        # the deployment the ISSUE targets: serving behind a sharded
        # ParallelInference, where EVERY launch pays multi-device dispatch
        # — the cost dynamic batching exists to amortize (on this CPU
        # proxy a 1-row sharded launch costs the same ~2 ms as a 32-row
        # one; a TPU pod slice behaves the same way)
        from deeplearning4j_tpu.parallel import ParallelInference

        baseline_model = ParallelInference(net, bucketize=False)  # old pad
        engine_model = ParallelInference(net)
    else:
        baseline_model = engine_model = net

    # --- locked baseline: global lock, one request per launch -------------
    lock = threading.Lock()

    def locked_predict(x):
        # host materialization included — the engine demux pays it too
        with lock:
            return np.asarray(baseline_model.output(x))

    def measure(predict):
        """Best round by req/s: the box is shared, a slow round means
        background contention, not a slower serving path."""
        best = None
        for _ in range(max(args.rounds, 1)):
            n_req, n_rows, lat, wall = _closed_loop(
                predict, args.clients, args.seconds, sizes, args.n_in)
            cur = {"req_per_s": round(n_req / wall, 1),
                   "rows_per_s": round(n_rows / wall, 1),
                   **_quantiles(lat)}
            if best is None or cur["req_per_s"] > best["req_per_s"]:
                best = cur
        return best

    for s in sizes:  # prime every request shape out of the measurement
        locked_predict(np.zeros((s, args.n_in), np.float32))
    results["locked"] = measure(locked_predict)

    # --- batched engine ---------------------------------------------------
    eng = InferenceEngine(engine_model, BatchingConfig(
        max_batch=args.max_batch, max_delay_ms=args.max_delay_ms,
        settle_ms=args.settle_ms),
        graph_opt=not args.no_graph_opt and args.backend != "pi")
    warm = eng.warmup()
    miss0 = aot_cache.stats()["misses"]
    results["batched"] = measure(eng.predict)
    recompiles = aot_cache.stats()["misses"] - miss0
    snap = telemetry.REGISTRY.snapshot(run_collectors=False)
    fill = snap.get("dl4j_serving_batch_fill_ratio", {})
    per_batch = snap.get("dl4j_serving_batch_requests", {})
    results["batched"].update({
        "warmup": warm,
        "recompiles_after_warmup": recompiles,
        "mean_fill_ratio": round(fill.get("mean", 0.0), 3),
        "mean_requests_per_launch": round(per_batch.get("mean", 0.0), 2),
    })
    eng.close()

    results["speedup"] = round(
        results["batched"]["req_per_s"]
        / max(results["locked"]["req_per_s"], 1e-9), 2)

    print(json.dumps(results, indent=2))
    with open(args.out, "w") as f:
        json.dump(results, f, indent=2)
    print(f"\nlocked  : {results['locked']['req_per_s']:>9} req/s   "
          f"p95 {results['locked']['p95_ms']} ms")
    print(f"batched : {results['batched']['req_per_s']:>9} req/s   "
          f"p95 {results['batched']['p95_ms']} ms")
    print(f"speedup : {results['speedup']}x   "
          f"(recompiles after warmup: {recompiles})")
    if args.assert_speedup and results["speedup"] < args.assert_speedup:
        print(f"FAIL: speedup {results['speedup']} < {args.assert_speedup}")
        return 1
    return 0


def smoke(args):
    """make serve-smoke: HTTP server up -> concurrent predicts ->
    /metrics scrape -> clean stop."""
    import urllib.request

    import numpy as np

    from deeplearning4j_tpu.optimize import aot_cache
    from deeplearning4j_tpu.parallel.batcher import BatchingConfig
    from deeplearning4j_tpu.parallel.serving import InferenceServer

    net = _build_net(args.n_in, args.hidden, args.n_out)
    server = InferenceServer(net, batching=BatchingConfig(
        max_batch=args.max_batch, max_delay_ms=args.max_delay_ms)
    ).start(port=0, warmup=True)
    base = f"http://127.0.0.1:{server.port}"
    miss0 = aot_cache.stats()["misses"]
    errors = []

    def client(ci):
        rng = np.random.default_rng(ci)
        for i in range(8):
            n = 1 + (ci + i) % 5
            x = rng.normal(size=(n, args.n_in)).astype(np.float32)
            req = urllib.request.Request(
                base + "/predict",
                json.dumps({"inputs": [x.tolist()]}).encode(),
                {"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=30) as r:
                body = json.loads(r.read())
            if len(body["outputs"][0]) != n:
                errors.append(f"client {ci}: demux row count mismatch")

    threads = [threading.Thread(target=client, args=(ci,))
               for ci in range(args.clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    text = urllib.request.urlopen(base + "/metrics",
                                  timeout=10).read().decode()
    server.stop()
    recompiles = aot_cache.stats()["misses"] - miss0
    ok = (not errors and recompiles == 0
          and "dl4j_serving_requests_total" in text
          and "dl4j_serving_batches_total" in text)
    print(f"serve-smoke: {args.clients} clients x 8 ragged predicts, "
          f"recompiles={recompiles}, errors={errors or 'none'}, "
          f"metrics={'ok' if 'dl4j_serving' in text else 'MISSING'}")
    print("OK" if ok else "FAIL")
    return 0 if ok else 1


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--clients", type=int, default=8)
    ap.add_argument("--seconds", type=float, default=3.0)
    ap.add_argument("--rounds", type=int, default=3,
                    help="measurement rounds per mode; best req/s wins")
    ap.add_argument("--sizes", default="1,2,3,4",
                    help="comma list of request row counts cycled per client")
    ap.add_argument("--max-batch", type=int, default=32)
    ap.add_argument("--max-delay-ms", type=float, default=2.0)
    ap.add_argument("--settle-ms", type=float, default=0.2)
    ap.add_argument("--backend", choices=("pi", "single"), default="pi",
                    help="pi = sharded ParallelInference deployment "
                         "(default), single = bare network")
    ap.add_argument("--n-in", type=int, default=64)
    ap.add_argument("--hidden", type=int, default=256)
    ap.add_argument("--n-out", type=int, default=10)
    ap.add_argument("--no-graph-opt", action="store_true")
    ap.add_argument("--out", default="bench_serving.json")
    ap.add_argument("--assert-speedup", type=float, default=0.0,
                    help="exit 1 if batched/locked speedup is below this")
    ap.add_argument("--smoke", action="store_true",
                    help="HTTP round-trip smoke instead of the benchmark")
    ap.add_argument("--tpu", action="store_true",
                    help="run on the real accelerator (default: CPU pin)")
    args = ap.parse_args()
    if not args.tpu:
        _pin_cpu()
    return smoke(args) if args.smoke else bench(args)


if __name__ == "__main__":
    sys.exit(main())
