"""End-to-end A/B: ResNet-50 production train step with the fused
1x1-conv+BN-stats Pallas kernel (``FusedConvBN1x1``, 36 sites) vs the
unfused reference topology — the round-3 verdict's missing measurement
(the kernel was only ever timed standalone, where the tunnel's per-op
noise swamps sub-ms deltas; 20-step aggregates x the projected ~8 ms/step
clear the >=50 ms measurement floor).

Protocol (BASELINE.md): batch 256 bf16 policy, device-cached batch
(write-back), 20 queued async steps + ONE value-forced sync per rep,
configs alternated A/B/A/B across reps so tunnel drift hits both arms,
min-of-reps reported. Run on-chip: ``python bench_fused_ab.py``.
"""

import dataclasses
import json
import time

import numpy as np

STEPS = 20
REPS = 3
BATCH = 256
IMG = 224
CLASSES = 1000


def build(fused):
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.conf.updaters import Adam
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.zoo.graphs import ResNet50

    model = ResNet50(num_classes=CLASSES, height=IMG, width=IMG,
                     updater=Adam(learning_rate=1e-3))
    model.stem_space_to_depth = True
    model.fused_conv_bn = fused
    cfg = dataclasses.replace(model.conf(), compute_dtype="bfloat16")
    return ComputationGraph(cfg).init()


def main():
    import jax

    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.zoo.graphs import ResNet50

    print(f"backend={jax.default_backend()} devices={jax.devices()}",
          flush=True)
    rng = np.random.default_rng(42)
    ds = DataSet(
        rng.integers(0, 256, (BATCH, IMG, IMG, 3), dtype=np.uint8),
        np.eye(CLASSES, dtype=np.float32)[rng.integers(0, CLASSES, BATCH)])

    nets = {}
    nets["unfused"] = build(False)
    nets["fused"] = build(True)
    # same weights on both arms (remap is 1:1)
    import jax.numpy as jnp

    p, s = ResNet50.fused_param_remap(
        jax.tree_util.tree_map(lambda a: np.asarray(a).copy(),
                               dict(nets["unfused"].params)),
        jax.tree_util.tree_map(lambda a: np.asarray(a).copy(),
                               dict(nets["unfused"].state)))
    nets["fused"].params = jax.tree_util.tree_map(jnp.asarray, p)
    nets["fused"].state = jax.tree_util.tree_map(jnp.asarray, s)

    results = {}
    for name, net in nets.items():
        for _ in range(3):  # compile + settle
            net.fit_batch(ds)
        results[f"{name}_times_ms"] = []

    for rep in range(REPS):
        for name, net in nets.items():
            t0 = time.perf_counter()
            for _ in range(STEPS):
                net._fit_batch_async(ds)
            _ = float(net.score_value)  # value-forced sync
            dt = (time.perf_counter() - t0) * 1000.0 / STEPS
            results[f"{name}_times_ms"].append(round(dt, 2))
            print(f"rep {rep} {name}: {dt:.2f} ms/step", flush=True)

    for name in nets:
        results[f"{name}_ms_per_step"] = min(results[f"{name}_times_ms"])
    a = results["unfused_ms_per_step"]
    b = results["fused_ms_per_step"]
    results["delta_ms"] = round(a - b, 2)
    results["speedup"] = round(a / b, 4)
    results["img_per_sec_unfused"] = round(BATCH / a * 1000.0, 1)
    results["img_per_sec_fused"] = round(BATCH / b * 1000.0, 1)
    print(json.dumps(results, indent=1))


if __name__ == "__main__":
    main()
