"""Conv-efficiency experiment matrix (round-5 verdict item #1).

The round-4 XProf trace put the ResNet-50 step's conv share at ~62-66 ms
against a ~16 ms bf16 roofline (~26% MXU over 234 fusions, largest
3.2 ms) and BASELINE.md called "that is XLA's conv efficiency" a
hypothesis. This script turns the hypothesis into measurements — the
committed experiment matrix the verdict asked for:

  1. **Batch sweep** (128 / 256 / 384 / 512) under the full round-4
     production config (bf16 policy, s2d stem, one-pass BN, uint8
     device-cached batch) — does more parallelism lift conv MXU
     occupancy, and what batch maximizes img/s?
  2. **XLA TPU flag probe** — `--xla_tpu_scoped_vmem_limit_kib` (bigger
     scoped vmem lets the Mosaic/XLA scheduler pipeline deeper) and
     latency-hiding-scheduler toggles, applied via child-process env
     (XLA flags are read at backend init, so each cell re-execs).
  3. **NCHW-vs-NHWC layout probe** — the dominant ResNet-50 conv shapes
     timed standalone (fwd and fwd+bwd) in both data layouts, bf16,
     isolating XLA's per-layout conv emitter efficiency from the
     end-to-end graph.

Protocol per cell (BASELINE.md): 3 compile/settle steps, then REPS x
STEPS queued async steps with ONE value-forced sync, min-of-reps —
identical to bench_fused_ab.py so cells are comparable with the round-4
A/B numbers. Run on-chip: ``python bench_conv_matrix.py`` (parent mode
spawns one child per cell; results land in bench_conv_matrix.json).
"""

import argparse
import dataclasses
import json
import os
import subprocess
import sys
import time

import numpy as np

STEPS = 20
REPS = 3
IMG = 224
CLASSES = 1000
OUT = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                   "bench_conv_matrix.json")


def build_net(batch):
    from deeplearning4j_tpu.conf.updaters import Adam
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.zoo.graphs import ResNet50

    model = ResNet50(num_classes=CLASSES, height=IMG, width=IMG,
                     updater=Adam(learning_rate=1e-3))
    model.stem_space_to_depth = True
    cfg = dataclasses.replace(model.conf(), compute_dtype="bfloat16")
    return ComputationGraph(cfg).init()


def child_train(batch):
    import jax

    from deeplearning4j_tpu.datasets.dataset import DataSet

    print(f"# backend={jax.default_backend()} batch={batch} "
          f"XLA_FLAGS={os.environ.get('XLA_FLAGS', '')!r}",
          file=sys.stderr, flush=True)
    net = build_net(batch)
    rng = np.random.default_rng(42)
    ds = DataSet(
        rng.integers(0, 256, (batch, IMG, IMG, 3), dtype=np.uint8),
        np.eye(CLASSES, dtype=np.float32)[
            rng.integers(0, CLASSES, batch)])
    for _ in range(3):
        net.fit_batch(ds)
    times = []
    for _ in range(REPS):
        t0 = time.perf_counter()
        for _ in range(STEPS):
            net._fit_batch_async(ds)
        _ = float(net.score_value)
        times.append((time.perf_counter() - t0) * 1000.0 / STEPS)
    ms = min(times)
    print(json.dumps({"ms_per_step": round(ms, 2),
                      "img_per_sec": round(batch / ms * 1000.0, 1),
                      "times_ms": [round(t, 2) for t in times]}))


# Dominant ResNet-50 conv shapes (NHWC: B,H,W,C x kh,kw,Cin,Cout). The
# 3x3s carry most FLOPs; the 1x1s dominate by count (the trace's 234
# fusions). Batch fixed at 256 to match the production cell.
PROBE_SHAPES = [
    ("res2_3x3", (56, 56, 64), (3, 3, 64, 64)),
    ("res3_3x3", (28, 28, 128), (3, 3, 128, 128)),
    ("res4_3x3", (14, 14, 256), (3, 3, 256, 256)),
    ("res5_3x3", (7, 7, 512), (3, 3, 512, 512)),
    ("res4_1x1_expand", (14, 14, 256), (1, 1, 256, 1024)),
    ("res4_1x1_reduce", (14, 14, 1024), (1, 1, 1024, 256)),
]


def child_layout(batch=256, chain=24):
    """Per-shape conv timing via IN-JIT chaining: one dispatch runs
    ``chain`` dependent conv applications (y_{i+1} = conv(y_i, W)), so
    the axon tunnel's ~10 ms per-call dispatch floor amortizes to
    <0.5 ms/conv. (The first version of this probe timed one conv per
    dispatch and measured a flat 10.6 ms for every cell — pure dispatch
    floor, zero signal.) The 1x1 expand/reduce pair chains as
    reduce(expand(x)). An im2col+dot_general variant of the 3x3 measures
    whether XLA's conv emitter leaves MXU matmul throughput on the
    table at the cost of 9x activation traffic."""
    import jax
    import jax.numpy as jnp

    results = {}

    def timed_chain(fn, x, label, n_ops):
        f = jax.jit(fn)
        out = f(x)
        jax.block_until_ready(out)
        _ = float(jnp.asarray(out).astype(jnp.float32).reshape(-1)[0])
        times = []
        for _ in range(REPS):
            t0 = time.perf_counter()
            outs = [f(x) for _ in range(4)]
            _ = float(jnp.asarray(outs[-1]).astype(
                jnp.float32).reshape(-1)[0])
            times.append((time.perf_counter() - t0) * 1000.0
                         / (4 * n_ops))
        results[label] = round(min(times), 3)

    for name, xs, ks in PROBE_SHAPES:
        h, w, cin = xs
        kh, kw, _, cout = ks
        rng = np.random.default_rng(0)
        x_nhwc = jnp.asarray(rng.normal(size=(batch, h, w, cin)),
                             jnp.bfloat16)
        scale = 1.0 / np.sqrt(kh * kw * cin)
        k_hwio = jnp.asarray(rng.normal(size=ks) * scale, jnp.bfloat16)
        x_nchw = jnp.transpose(x_nhwc, (0, 3, 1, 2))
        k_oihw = jnp.transpose(k_hwio, (3, 2, 0, 1))
        paired = cin != cout
        if paired:
            k2_hwio = jnp.asarray(
                rng.normal(size=(kh, kw, cout, cin)) / np.sqrt(
                    kh * kw * cout), jnp.bfloat16)
            k2_oihw = jnp.transpose(k2_hwio, (3, 2, 0, 1))

        def chain_fwd(x, k, k2, dn, n):
            def body(_, y):
                y = jax.lax.conv_general_dilated(
                    y, k, (1, 1), "SAME", dimension_numbers=dn)
                if k2 is not None:
                    y = jax.lax.conv_general_dilated(
                        y, k2, (1, 1), "SAME", dimension_numbers=dn)
                return y
            # static bounds -> scan lowering -> reverse-differentiable
            return jax.lax.fori_loop(0, n, body, x)

        def chain_bwd(x, k, k2, dn, n):
            # d(chain)/dk: fwd chain + full reverse sweep in one
            # program; shorter chain than fwd — the scan saves one
            # activation residual per iteration (res2's 103 MB x 24
            # would brush the 16 GB HBM)
            def loss(kk):
                return jnp.sum(
                    chain_fwd(x, kk, k2, dn, n).astype(jnp.float32))
            return jax.grad(loss)(k)

        for layout, x, k, k2, dn in (
                ("nhwc", x_nhwc, k_hwio,
                 k2_hwio if paired else None, ("NHWC", "HWIO", "NHWC")),
                ("nchw", x_nchw, k_oihw,
                 k2_oihw if paired else None, ("NCHW", "OIHW", "NCHW"))):
            # close over k/k2/dn (dn is a static string tuple — passing
            # it through jit as an argument would fail to trace)
            nf = chain // 2 if paired else chain
            nb = max(nf // 3, 4)
            timed_chain(lambda x, k=k, k2=k2, dn=dn, n=nf:
                        chain_fwd(x, k, k2, dn, n),
                        x, f"{name}_{layout}_fwd_ms",
                        n_ops=nf * (2 if paired else 1))
            timed_chain(lambda x, k=k, k2=k2, dn=dn, n=nb:
                        chain_bwd(x, k, k2, dn, n),
                        x, f"{name}_{layout}_fwd+bwd_ms",
                        n_ops=nb * (2 if paired else 1))

        if (kh, kw) == (3, 3):
            # im2col: patches [B*H*W, 9*Cin] @ [9*Cin, Cout]
            kmat = k_hwio.reshape(kh * kw * cin, cout)

            def im2col_fwd(x, kmat=kmat):
                def body(_, y):
                    p = jax.lax.conv_general_dilated_patches(
                        y, (kh, kw), (1, 1), "SAME",
                        dimension_numbers=("NHWC", "HWIO", "NHWC"))
                    z = jax.lax.dot_general(
                        p.reshape(-1, kh * kw * cin), kmat,
                        (((1,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32)
                    return z.reshape(batch, h, w, cout).astype(
                        jnp.bfloat16)
                return jax.lax.fori_loop(0, chain, body, x)

            timed_chain(im2col_fwd, x_nhwc, f"{name}_im2col_fwd_ms",
                        n_ops=chain)

        # bf16 MXU roofline (fwd): 2*B*H*W*Cin*Cout*kh*kw FLOPs at
        # ~197 TFLOP/s (v5e bf16 peak; the FIRST probe run used 394 —
        # the v5p number — so this run's mxu_pct is 2x the first's)
        flops = 2 * batch * h * w * cin * cout * kh * kw
        results[f"{name}_roofline_fwd_ms"] = round(
            flops / 197e12 * 1000.0, 3)
        results[f"{name}_mxu_pct_nhwc_fwd"] = round(
            100.0 * results[f"{name}_roofline_fwd_ms"]
            / max(results[f"{name}_nhwc_fwd_ms"], 1e-9), 1)
        print(f"# {name}: {json.dumps({k2: v for k2, v in results.items() if k2.startswith(name)})}",
              file=sys.stderr, flush=True)

    # MXU reference: chained square bf16 matmuls — what this chip (and
    # tunnel session) can ACTUALLY sustain, the denominator that decides
    # whether the conv numbers above are "XLA leaving 4x on the table"
    # or "the achievable roof". 4096^3: 137 GFLOP/op.
    for dim in (2048, 4096, 8192):
        a = jnp.asarray(np.random.default_rng(2).normal(
            size=(dim, dim)) / np.sqrt(dim), jnp.bfloat16)

        def mm_chain(x, n=chain):
            def body(_, y):
                return jax.lax.dot_general(
                    y, a, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.bfloat16)
            return jax.lax.fori_loop(0, n, body, x)

        timed_chain(mm_chain, a, f"matmul{dim}_fwd_ms", n_ops=chain)
        rl = 2 * dim ** 3 / 197e12 * 1000.0
        results[f"matmul{dim}_roofline_ms"] = round(rl, 3)
        results[f"matmul{dim}_mxu_pct"] = round(
            100.0 * rl / max(results[f"matmul{dim}_fwd_ms"], 1e-9), 1)
        print(f"# matmul{dim}: {results[f'matmul{dim}_fwd_ms']} ms "
              f"({results[f'matmul{dim}_mxu_pct']}% of v5e bf16 peak)",
              file=sys.stderr, flush=True)
    print(json.dumps(results))


def child_kernels(batch=8, img=8, steps=8, out_path=None, smoke=False):
    """Pallas kernel-registry A/B on a small fused-conv net (ROADMAP
    item 5): autotune every routable envelope at this batch, then train
    FRESH nets per mode — stock XLA vs ``use_kernels`` — on the same
    stream, reporting img/s per mode, recompiles-after-warmup (asserted
    0 for both), and the final-params max |delta| (the parity record).

    Sized for the CPU proxy: off-TPU the kernels execute through the
    Pallas INTERPRETER, so the kernels-mode img/s measures the
    interpreter, not the MXU — the committed JSON records parity, the
    zero-recompile contract, and the autotuner machinery; speed claims
    need the TPU backend (docs/kernels.md states the caveat)."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu import kernels as kern
    from deeplearning4j_tpu.conf import inputs as it
    from deeplearning4j_tpu.conf.activations import Activation
    from deeplearning4j_tpu.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.conf.layers_cnn import FusedConvBN1x1
    from deeplearning4j_tpu.conf.multilayer import NeuralNetConfiguration
    from deeplearning4j_tpu.conf.updaters import Adam
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.optimize import aot_cache

    def conf(use_k):
        b = NeuralNetConfiguration.builder().seed(42).updater(
            Adam(learning_rate=1e-3))
        if use_k:
            b = b.use_kernels()
        return (b.list()
                .layer(FusedConvBN1x1(n_out=16,
                                      activation=Activation.RELU))
                .layer(FusedConvBN1x1(n_out=16,
                                      activation=Activation.RELU))
                .layer(DenseLayer(n_out=32, activation=Activation.RELU))
                .layer(OutputLayer(n_out=10))
                .set_input_type(it.Convolutional(img, img, 8))
                .build())

    rng = np.random.default_rng(0)
    X = rng.normal(size=(batch, img, img, 8)).astype(np.float32)
    Y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, batch)]

    results = {"backend": kern.capability(), "batch": batch, "img": img,
               "steps": steps}
    t0 = time.perf_counter()
    tuned = kern.autotune_model(conf(True), batch, max_candidates=8)
    results["autotune_s"] = round(time.perf_counter() - t0, 2)
    results["tuned_envelopes"] = len(tuned)
    results["winners"] = {r.env_key: list(r.tiling) for r in tuned}

    def run(use_k, label):
        net = MultiLayerNetwork(conf(use_k)).init()
        ds = DataSet(X.copy(), Y.copy())
        net.fit_batch(ds)  # compile + settle
        net.fit_batch(ds)
        miss0 = aot_cache.stats()["misses"]
        t0 = time.perf_counter()
        for _ in range(steps):
            net._fit_batch_async(ds)
        _ = float(net.score_value)
        wall = time.perf_counter() - t0
        results[f"img_per_sec_{label}"] = round(steps * batch / wall, 1)
        results[f"recompiles_after_warmup_{label}"] = (
            aot_cache.stats()["misses"] - miss0)
        return net

    net_a = run(False, "xla")
    net_b = run(True, "kernels")
    results["params_max_delta"] = max(
        float(jnp.max(jnp.abs(jnp.asarray(a, jnp.float32)
                              - jnp.asarray(b, jnp.float32))))
        for a, b in zip(jax.tree_util.tree_leaves(net_a.params),
                        jax.tree_util.tree_leaves(net_b.params)))
    results["note"] = (
        "CPU proxy: kernels ran through the Pallas interpreter — "
        "img_per_sec_kernels measures the interpreter, not the MXU; "
        "the record here is parity + zero recompiles + the tuned "
        "winner set. Re-run on a TPU backend for speed."
        if results["backend"] != "tpu" else
        "TPU backend: real Mosaic lowering.")
    assert results["recompiles_after_warmup_xla"] == 0, results
    assert results["recompiles_after_warmup_kernels"] == 0, results
    if smoke:
        assert results["tuned_envelopes"] >= 2, results
        assert results["params_max_delta"] < 1e-3, results
    blob = json.dumps(results, indent=1)
    print(blob)
    if out_path:
        with open(out_path, "w") as f:
            f.write(blob + "\n")
        print(f"# wrote {out_path}", file=sys.stderr)


CELLS = [
    # (cell name, kind, batch, extra XLA flags)
    ("b128", "train", 128, ""),
    ("b256_control", "train", 256, ""),
    ("b384", "train", 384, ""),
    ("b512", "train", 512, ""),
    ("b256_vmem64m", "train", 256,
     "--xla_tpu_scoped_vmem_limit_kib=65536"),
    ("b256_vmem128m", "train", 256,
     "--xla_tpu_scoped_vmem_limit_kib=131072"),
    ("b256_no_lhs", "train", 256,
     "--xla_tpu_enable_latency_hiding_scheduler=false"),
    # the axon XLA build fatals on unknown --xla_tpu_* in XLA_FLAGS
    # (measured above); libtpu-style flags go via LIBTPU_INIT_ARGS —
    # probe whether the tunnel forwards them
    ("b256_libtpu_vmem", "train", 256,
     "LIBTPU:--xla_tpu_scoped_vmem_limit_kib=65536"),
    ("layout_probe", "layout", 256, ""),
]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--child", choices=["train", "layout"])
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--cells", default="",
                    help="comma-separated subset of cell names")
    ap.add_argument("--kernels", action="store_true",
                    help="in-process Pallas kernel-registry A/B "
                         "(stock XLA vs use_kernels, fresh nets per "
                         "mode; CPU-proxy sized — see child_kernels)")
    ap.add_argument("--kernels-batch", type=int, default=8)
    ap.add_argument("--kernels-img", type=int, default=8)
    ap.add_argument("--kernels-steps", type=int, default=8)
    ap.add_argument("--smoke", action="store_true",
                    help="with --kernels: assert parity + tuned "
                         "envelopes (make kernels-smoke)")
    ap.add_argument("--out", default="",
                    help="with --kernels: also write the JSON here")
    args = ap.parse_args()
    if args.kernels:
        child_kernels(args.kernels_batch, args.kernels_img,
                      args.kernels_steps, out_path=args.out or None,
                      smoke=args.smoke)
        return
    if args.child == "train":
        child_train(args.batch)
        return
    if args.child == "layout":
        child_layout(args.batch)
        return

    want = set(filter(None, args.cells.split(",")))
    results = {}
    if os.path.exists(OUT):
        results = json.load(open(OUT))
    for name, kind, batch, flags in CELLS:
        if want and name not in want:
            continue
        env = dict(os.environ)
        if flags.startswith("LIBTPU:"):
            env["LIBTPU_INIT_ARGS"] = flags[len("LIBTPU:"):]
        elif flags:
            env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                                + " " + flags).strip()
        cmd = [sys.executable, os.path.abspath(__file__),
               "--child", kind, "--batch", str(batch)]
        print(f"== {name}: {' '.join(cmd)} flags={flags!r}", flush=True)
        t0 = time.perf_counter()
        proc = subprocess.run(cmd, env=env, capture_output=True,
                              text=True, timeout=1200)
        wall = time.perf_counter() - t0
        line = proc.stdout.strip().splitlines()[-1] if proc.stdout.strip() \
            else ""
        try:
            cell = json.loads(line)
        except (json.JSONDecodeError, ValueError):
            cell = {"error": (proc.stderr or proc.stdout)[-800:],
                    "rc": proc.returncode}
        cell["wall_s"] = round(wall, 1)
        cell["flags"] = flags
        results[name] = cell
        print(json.dumps({name: cell}), flush=True)
        json.dump(results, open(OUT, "w"), indent=1)
    print(f"wrote {OUT}")


if __name__ == "__main__":
    main()
