"""Benchmark harness — runs on the real TPU chip (default env platform).

Prints ONE JSON line: {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}.

Current flagship benchmark: LeNet/MNIST training throughput (BASELINE
config #1). The reference ships no published numbers (BASELINE.md), so the
first measured value defines the baseline; vs_baseline is measured/baseline
once BENCH_BASELINE.json exists (written on first run), else 1.0.

Protocol (BASELINE.md): median of >=3 timed runs, first (compile) step
excluded, fixed batch size, per-chip numbers.
"""

import json
import statistics
import time
from pathlib import Path

import numpy as np

BATCH = 256
STEPS_PER_RUN = 30
RUNS = 4
BASELINE_FILE = Path(__file__).parent / "BENCH_BASELINE.json"


def main():
    import jax

    from deeplearning4j_tpu.conf.updaters import Adam
    from deeplearning4j_tpu.datasets.mnist import synthesize
    from deeplearning4j_tpu.zoo.models import LeNet

    devices = jax.devices()
    net = LeNet(updater=Adam(learning_rate=1e-3)).init()

    features, labels = synthesize(BATCH, seed=42)
    from deeplearning4j_tpu.datasets.dataset import DataSet

    ds = DataSet(features, labels)

    # warmup: first step compiles
    net.fit_batch(ds)
    _ = net.score_value  # sync

    run_rates = []
    for _ in range(RUNS):
        t0 = time.perf_counter()
        for _ in range(STEPS_PER_RUN):
            net.fit_batch(ds)
        # fit_batch converts loss to float -> device sync included
        dt = time.perf_counter() - t0
        run_rates.append(STEPS_PER_RUN * BATCH / dt)

    images_per_sec = statistics.median(run_rates)

    if BASELINE_FILE.exists():
        base = json.loads(BASELINE_FILE.read_text()).get("images_per_sec")
    else:
        base = images_per_sec
        BASELINE_FILE.write_text(json.dumps({
            "images_per_sec": images_per_sec,
            "config": "LeNet/MNIST train, batch=256",
            "device": str(devices[0]),
        }))
    vs = images_per_sec / base if base else 1.0

    print(json.dumps({
        "metric": "lenet_mnist_train_images_per_sec_per_chip",
        "value": round(images_per_sec, 1),
        "unit": "images/sec",
        "vs_baseline": round(vs, 3),
    }))


if __name__ == "__main__":
    main()
