"""Benchmark harness — runs on the real TPU chip (default env platform).

Prints ONE JSON line: {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}.

Headline benchmark (SURVEY.md §6 / BASELINE.json): **ResNet-50 training
images/sec/chip** (dl4j-zoo ResNet50 equivalent, BASELINE config #2). The
reference ships no published numbers (BASELINE.md), so the first measured
value defines the baseline; vs_baseline = measured/recorded once
BENCH_BASELINE.json exists (written on first run, keyed per metric).

Protocol (BASELINE.md): median of >=3 timed runs, compile excluded, fixed
batch size, per-chip numbers. Whole-graph jitted train step (forward +
backward + Adam fused into one XLA program) — the TPU-native inversion of
the reference's per-op JNI dispatch.
"""

import json
import statistics
import time
from pathlib import Path

import numpy as np

N_BATCHES = 12

METRIC = "resnet50_train_images_per_sec_per_chip"
BATCH = 256
IMG = 224
CLASSES = 1000
RUNS = 5
BASELINE_FILE = Path(__file__).parent / "BENCH_BASELINE.json"


def main():
    import dataclasses

    import jax

    from deeplearning4j_tpu.conf.updaters import Adam
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.zoo.graphs import ResNet50

    devices = jax.devices()
    # protocol v4: batch 256 + the bf16 compute policy (f32 master params,
    # bf16 forward/backward — conf.compute_dtype). Measured on v5e: device
    # step 64ms -> 34ms at batch 64, 115ms at batch 256 (2.2x throughput);
    # see BASELINE.md MFU table.
    model = ResNet50(num_classes=CLASSES, height=IMG, width=IMG,
                     updater=Adam(learning_rate=1e-3))
    # EXACT space-to-depth stem rewrite (MLPerf trick; equivalence pinned
    # by tests/test_zoo.py) — measured ~4% device fwd+bwd win, BASELINE.md
    # round-3 MFU section
    model.stem_space_to_depth = True
    cfg = dataclasses.replace(model.conf(), compute_dtype="bfloat16")
    net = ComputationGraph(cfg).init()

    from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator

    rng = np.random.default_rng(42)
    # uint8 image batches: the realistic image-pipeline dtype. They cross
    # the host->device link as bytes (4x less traffic — the link, not the
    # MXU, bounds this chip's step time) and are dequantized to [0,1]
    # floats INSIDE the compiled step (ImagePreProcessingScaler's math
    # moved on-device).
    batches = [DataSet(
        rng.integers(0, 256, (BATCH, IMG, IMG, 3), dtype=np.uint8),
        np.eye(CLASSES, dtype=np.float32)[rng.integers(0, CLASSES, BATCH)])
        for _ in range(N_BATCHES)]
    it = ListDataSetIterator(batches)

    # warmup: first step compiles; a few extra steps settle the tunnel's
    # post-compile transfer path (BASELINE.md notes the variance)
    for _ in range(3):
        net.fit_batch(batches[0])
    _ = net.score_value  # sync

    run_rates = []
    for _ in range(RUNS):
        t0 = time.perf_counter()
        # fit() overlaps host->device transfer and dispatch with compute
        # (bounded async depth); epoch end syncs
        net.fit(it, epochs=1)
        dt = time.perf_counter() - t0
        run_rates.append(N_BATCHES * BATCH / dt)

    images_per_sec = statistics.median(run_rates)

    baselines = {}
    if BASELINE_FILE.exists():
        baselines = json.loads(BASELINE_FILE.read_text())
        # migrate pre-graph-zoo flat format {"images_per_sec": ...} to the
        # per-metric format, preserving the recorded LeNet baseline
        if "images_per_sec" in baselines:
            baselines = {"lenet_mnist_train_images_per_sec_per_chip": {
                "value": baselines["images_per_sec"],
                "config": baselines.get("config", ""),
                "device": baselines.get("device", ""),
            }}
    if METRIC not in baselines:
        baselines[METRIC] = {
            "value": images_per_sec,
            "config": f"ResNet50 train, batch={BATCH}, {IMG}x{IMG}x3 uint8 in, "
                      f"{CLASSES} classes, f32 params + bf16 compute policy",
            "device": str(devices[0]),
        }
        BASELINE_FILE.write_text(json.dumps(baselines, indent=2))
    base = baselines[METRIC]["value"]
    vs = images_per_sec / base if base else 1.0

    # honest round-over-round ratios (round-2 verdict: vs_baseline's
    # denominator is the protocol-v1 number — 28.1 img/s, per-step-synced
    # f32 host inputs — so it mostly measures protocol evolution, not this
    # round's work; vs_round{N} divides by the driver-recorded same-
    # protocol result of each earlier round)
    out = {
        "metric": METRIC,
        "value": round(images_per_sec, 1),
        "unit": "images/sec",
        "vs_baseline": round(vs, 3),
    }
    for n in (1, 2):
        f = Path(__file__).parent / f"BENCH_r{n:02d}.json"
        if f.exists():
            try:
                prev = json.loads(f.read_text())
                prev = prev.get("parsed", prev)  # driver wraps the JSON line
                if prev.get("metric") == METRIC and prev.get("value"):
                    out[f"vs_round{n}"] = round(
                        images_per_sec / float(prev["value"]), 3)
            except Exception:
                pass  # a malformed round file must not eat the bench result
    print(json.dumps(out))


if __name__ == "__main__":
    main()
