"""Conv+BN+ReLU fusion experiment at ResNet-50 stage shapes (round-2
verdict item #2: try the Pallas BN-epilogue experiment and commit the
result, positive or negative — BASELINE.md carries the conclusion).

For each shape (stem 7x7/s2, stage-1 1x1 and 3x3, stage-1 1x1 expand),
batch 256 bf16:
- conv only (XLA);
- conv + train-mode BN (batch stats) + ReLU (XLA fusion);
- for 1x1 convs: a Pallas kernel computing the matmul AND the per-channel
  sum / sum-of-squares in ONE output pass (the BN-stats read of y is
  folded into the matmul epilogue; the normalize+ReLU pass still reads y
  once). XLA's schedule is write-y, read-y-for-stats, read-y-normalize —
  the kernel removes one full activation pass.

Protocol as bench_resnet_profile.py: N queued calls + one value sync,
min of 3, null round-trip subtracted.
"""

import functools
import json
import time

import numpy as np

N = 40
B = 256


def main():
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    def _sync(x):
        return float(jnp.asarray(x).astype(jnp.float32).reshape(-1)[0])

    null = jax.jit(lambda v: v + 1.0)
    _sync(null(jnp.float32(0.0)))
    rts = []
    for _ in range(5):
        t0 = time.perf_counter()
        out = jnp.float32(0.0)
        for _ in range(10):
            out = null(out)
        _sync(out)
        rts.append((time.perf_counter() - t0) * 1000.0)
    rt = min(rts)

    def timed(fn, *args):
        out = fn(*args)
        _sync(out)
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(N):
                out = fn(*args)
            _sync(out)
            best = min(best, ((time.perf_counter() - t0) * 1000.0 - rt) / N)
        return best

    rng = np.random.default_rng(0)

    def mk(shape):
        return jnp.asarray(rng.normal(size=shape).astype(np.float32),
                           jnp.bfloat16)

    SHAPES = [
        ("stem7x7s2", (B, 224, 224, 3), (7, 7, 3, 64), (2, 2), "SAME"),
        ("s1_1x1", (B, 56, 56, 64), (1, 1, 64, 64), (1, 1), "VALID"),
        ("s1_3x3", (B, 56, 56, 64), (3, 3, 64, 64), (1, 1), "SAME"),
        ("s1_1x1x4", (B, 56, 56, 64), (1, 1, 64, 256), (1, 1), "VALID"),
    ]
    results = {"null_roundtrip_ms": round(rt, 1)}
    dn = ("NHWC", "HWIO", "NHWC")

    def conv(x, w, s, p):
        return jax.lax.conv_general_dilated(x, w, s, p,
                                            dimension_numbers=dn)

    def conv_bn_relu(x, w, s, p, gamma, beta):
        y = conv(x, w, s, p)
        y32 = y.astype(jnp.float32)
        mean = jnp.mean(y32, axis=(0, 1, 2))
        var = jnp.var(y32, axis=(0, 1, 2))
        yh = (y32 - mean) * jax.lax.rsqrt(var + 1e-5) * gamma + beta
        return jnp.maximum(yh, 0.0).astype(x.dtype)

    for name, xs, ws, s, p in SHAPES:
        x, w = mk(xs), mk(ws)
        cout = ws[-1]
        gamma = jnp.ones((cout,), jnp.float32)
        beta = jnp.zeros((cout,), jnp.float32)
        f1 = jax.jit(lambda x, w, _s=s, _p=p: conv(x, w, _s, _p)
                     .astype(jnp.float32).sum())
        results[f"{name}_conv_ms"] = round(timed(f1, x, w), 2)
        f2 = jax.jit(lambda x, w, g, b, _s=s, _p=p:
                     conv_bn_relu(x, w, _s, _p, g, b)
                     .astype(jnp.float32).sum())
        results[f"{name}_conv_bn_relu_ms"] = round(
            timed(f2, x, w, gamma, beta), 2)

    # ---- Pallas fused 1x1-conv (matmul) + BN-stats single pass ----
    # grid over (row blocks, col blocks); the kernel writes the y tile and
    # accumulates per-channel sum / sumsq into per-row-block partials
    # (reduced outside — tiny [nbm, C] arrays), so y is READ ZERO extra
    # times for statistics.
    BM, BN_, BK = 512, 128, 128

    def fused_kernel(x_ref, w_ref, y_ref, s_ref, q_ref, acc, *, nk):
        k = pl.program_id(2)

        @pl.when(k == 0)
        def _():
            acc[...] = jnp.zeros_like(acc)

        acc[...] += jax.lax.dot(x_ref[...], w_ref[...],
                                preferred_element_type=jnp.float32)

        @pl.when(k == nk - 1)
        def _():
            y = acc[...]
            y_ref[...] = y.astype(y_ref.dtype)
            s_ref[...] = jnp.sum(y, axis=0).reshape(s_ref.shape)
            q_ref[...] = jnp.sum(y * y, axis=0).reshape(q_ref.shape)

    def fused_1x1_bn_relu(x, w, gamma, beta):
        b, h, wd, cin = x.shape
        cout = w.shape[-1]
        m = b * h * wd
        x2 = x.reshape(m, cin)
        w2 = w.reshape(cin, cout)
        nbm, nbn, nbk = m // BM, max(cout // BN_, 1), max(cin // BK, 1)
        bn_ = min(BN_, cout)
        bk = min(BK, cin)
        y, ssum, sq = pl.pallas_call(
            functools.partial(fused_kernel, nk=nbk),
            grid=(nbm, nbn, nbk),
            in_specs=[pl.BlockSpec((BM, bk), lambda i, j, k: (i, k)),
                      pl.BlockSpec((bk, bn_), lambda i, j, k: (k, j))],
            out_specs=[pl.BlockSpec((BM, bn_), lambda i, j, k: (i, j)),
                       pl.BlockSpec((1, 1, bn_), lambda i, j, k: (i, 0, j)),
                       pl.BlockSpec((1, 1, bn_), lambda i, j, k: (i, 0, j))],
            out_shape=[
                jax.ShapeDtypeStruct((m, cout), x.dtype),
                jax.ShapeDtypeStruct((nbm, 1, cout), jnp.float32),
                jax.ShapeDtypeStruct((nbm, 1, cout), jnp.float32),
            ],
            scratch_shapes=[pltpu.VMEM((BM, bn_), jnp.float32)],
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("parallel", "parallel", "arbitrary")),
        )(x2, w2)
        mean = jnp.sum(ssum[:, 0], axis=0) / m
        var = jnp.sum(sq[:, 0], axis=0) / m - mean * mean
        yh = (y.astype(jnp.float32) - mean) * jax.lax.rsqrt(var + 1e-5) \
            * gamma + beta
        return jnp.maximum(yh, 0.0).astype(x.dtype).reshape(b, h, wd, cout)

    for name, xs, ws in [("s1_1x1", (B, 56, 56, 64), (1, 1, 64, 64)),
                         ("s1_1x1x4", (B, 56, 56, 64), (1, 1, 64, 256))]:
        x, w = mk(xs), mk(ws)
        cout = ws[-1]
        gamma = jnp.ones((cout,), jnp.float32)
        beta = jnp.zeros((cout,), jnp.float32)
        fp = jax.jit(lambda x, w, g, b: fused_1x1_bn_relu(x, w, g, b)
                     .astype(jnp.float32).sum())
        # correctness vs the XLA reference first
        ref = conv_bn_relu(x, w, (1, 1), "VALID", gamma, beta)
        got = fused_1x1_bn_relu(x, w, gamma, beta)
        err = float(jnp.max(jnp.abs(got.astype(jnp.float32)
                                    - ref.astype(jnp.float32))))
        results[f"{name}_pallas_fused_maxerr"] = round(err, 4)
        results[f"{name}_pallas_fused_ms"] = round(
            timed(fp, x, w, gamma, beta), 2)

    print(json.dumps(results, indent=1))


if __name__ == "__main__":
    main()
