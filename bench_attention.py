"""Reproducible attention-path benchmark (the source of BASELINE.md's
attention table and of ``dot_product_attention``'s dispatch thresholds).

Protocol (see BASELINE.md measurement notes — ``block_until_ready`` on the
axon tunnel returns at dispatch, so syncs must force a VALUE):

- shapes: B4 / H8 / D64, bf16, causal self-attention, T swept;
- jitted closure per (impl, mode); 2 warmup calls (compile + settle);
- time N enqueued calls (default 20 — the tunnel's fixed ~20ms
  enqueue+sync round-trip must amortize below the per-call compute, or
  sub-30ms configs all measure the same), then force one scalar from the
  LAST output; report per-call ms. OOM / compile failures are recorded,
  not fatal.

Run on the real chip (no env overrides needed):  python bench_attention.py
Optional: ``--json`` emits one JSON line per measurement for tooling.

The dispatcher rule derived from this script's output is encoded in
``deeplearning4j_tpu/ops/attention.py::dot_product_attention`` — if the two
ever disagree on-chip, re-run this script and fix the dispatcher, not the
table.
"""

import argparse
import json
import time

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.ops.attention import (
    blockwise_attention,
    flash_attention,
    reference_attention,
)

B, H, D = 4, 8, 64
N_CALLS = 20
WARMUP = 2

IMPLS = {
    "reference": reference_attention,
    "blockwise": blockwise_attention,
    "flash": flash_attention,
}


def _inputs(t, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(  # noqa: E731
        rng.normal(size=(B, H, t, D)).astype(np.float32), jnp.bfloat16)
    return mk(), mk(), mk()


def _force(out):
    """Value-forced sync: pull one scalar from the first leaf."""
    leaf = jax.tree_util.tree_leaves(out)[0]
    return float(jnp.asarray(leaf).reshape(-1)[0].astype(jnp.float32))


def measure(impl: str, mode: str, t: int):
    """-> per-call ms (float) or an error string."""
    fn = IMPLS[impl]
    q, k, v = _inputs(t)
    if mode == "fwd":
        step = jax.jit(lambda q, k, v: fn(q, k, v, causal=True))
    else:  # fwd+bwd: gradient wrt q, k, v of a scalar readout
        step = jax.jit(jax.grad(
            lambda q, k, v: fn(q, k, v, causal=True)
            .astype(jnp.float32).sum(), argnums=(0, 1, 2)))
    try:
        for _ in range(WARMUP):
            out = step(q, k, v)
        _force(out)
        t0 = time.perf_counter()
        for _ in range(N_CALLS):
            out = step(q, k, v)
        _force(out)
        return (time.perf_counter() - t0) / N_CALLS * 1000.0
    except Exception as e:  # OOM at compile/run, kernel unsupported, ...
        return f"{type(e).__name__}"


def main():
    global N_CALLS
    ap = argparse.ArgumentParser()
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--n", type=int, default=N_CALLS,
                    help="queued calls per measurement")
    ap.add_argument("--ts", type=int, nargs="*",
                    default=[1024, 2048, 4096, 8192, 16384])
    args = ap.parse_args()
    N_CALLS = args.n

    backend = jax.default_backend()
    rows = []
    for t in args.ts:
        for mode in ("fwd", "fwd+bwd"):
            for impl in ("reference", "blockwise", "flash"):
                # full materialization at T>=8192 is pointless (and the
                # [B,H,T,T] matrix alone is >= 4 GB): skip, like the judge
                if impl == "reference" and t > 4096:
                    rows.append((t, mode, impl, "skipped"))
                    continue
                ms = measure(impl, mode, t)
                rows.append((t, mode, impl, ms))
                if args.json:
                    print(json.dumps({
                        "bench": "attention", "backend": backend,
                        "B": B, "H": H, "D": D, "T": t, "mode": mode,
                        "impl": impl,
                        "ms": ms if isinstance(ms, float) else None,
                        "error": None if isinstance(ms, float) else ms,
                    }), flush=True)

    print(f"\nbackend={backend}  B{B}/H{H}/D{D} bf16 causal  "
          f"(N={N_CALLS} queue-timed, value-forced sync)\n")
    print(f"{'T':>6} {'mode':>8} | {'reference':>12} {'blockwise':>12} "
          f"{'flash':>12}")
    by_key = {(t, m, i): v for t, m, i, v in rows}
    for t in args.ts:
        for mode in ("fwd", "fwd+bwd"):
            cells = []
            for impl in ("reference", "blockwise", "flash"):
                v = by_key[(t, mode, impl)]
                cells.append(f"{v:>10.1f}ms" if isinstance(v, float)
                             else f"{v:>12}")
            print(f"{t:>6} {mode:>8} | " + " ".join(cells))


if __name__ == "__main__":
    main()
