"""Reproducible attention-path benchmark, two harnesses in one file:

1. **Impl sweep** (default; the source of BASELINE.md's attention table
   and of ``dot_product_attention``'s dispatch thresholds): jitted
   reference / blockwise / flash closures, B4/H8/D64 bf16 causal, T
   swept. Protocol per BASELINE.md measurement notes —
   ``block_until_ready`` on the axon tunnel returns at dispatch, so
   syncs must force a VALUE: 2 warmup calls, time N enqueued calls
   (default 20), force one scalar from the LAST output, report per-call
   ms. OOM / compile failures are recorded, not fatal. The dispatcher
   rule derived from this sweep lives in
   ``deeplearning4j_tpu/ops/attention.py::dot_product_attention`` — if
   the two ever disagree on-chip, re-run this script and fix the
   dispatcher, not the table.

2. **Kernel-registry A/B** (``--kernels``; the ISSUE-17 acceptance
   harness, committed as ``BENCH_attention_r01.json``): the tuned
   ``flash_attention`` registry kernel vs the stock XLA reference
   across sequence lengths (fwd and fwd+bwd — the custom-VJP backward
   is part of the contract), the ``paged_decode_attention`` gather vs
   the masked full-cache ``decode_attention`` read across cache
   OCCUPANCIES (the paged kernel's cost is O(used pages); the masked
   read always pays the full bucket), and an end-to-end decoder leg:
   stock vs ``use_kernels=True`` ``TransformerDecoder`` generation,
   asserting greedy token identity and ZERO recompiles after warmup
   with ``kern:`` tokens in every step key. ``--smoke`` shrinks every
   axis and turns the assertions on (``make attention-smoke``).

Honest CPU-proxy caveat (same as docs/kernels.md): off-TPU every
kernel body runs through the Pallas INTERPRETER, so kernel-leg
timings rank the interpreter, not the MXU — the committed record is
parity + token identity + zero recompiles + the tuned winner set, not
speed. The A/B speed claim requires ``--tpu`` on a real chip.

Run on the real chip (no env overrides needed):  python bench_attention.py
Optional: ``--json`` emits one JSON line per measurement for tooling.
"""

import argparse
import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.ops.attention import (
    blockwise_attention,
    flash_attention,
    reference_attention,
)

B, H, D = 4, 8, 64
N_CALLS = 20
WARMUP = 2

IMPLS = {
    "reference": reference_attention,
    "blockwise": blockwise_attention,
    "flash": flash_attention,
}


def _inputs(t, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(  # noqa: E731
        rng.normal(size=(B, H, t, D)).astype(np.float32), jnp.bfloat16)
    return mk(), mk(), mk()


def _force(out):
    """Value-forced sync: pull one scalar from the first leaf."""
    leaf = jax.tree_util.tree_leaves(out)[0]
    return float(jnp.asarray(leaf).reshape(-1)[0].astype(jnp.float32))


def measure(impl: str, mode: str, t: int):
    """-> per-call ms (float) or an error string."""
    fn = IMPLS[impl]
    q, k, v = _inputs(t)
    if mode == "fwd":
        step = jax.jit(lambda q, k, v: fn(q, k, v, causal=True))
    else:  # fwd+bwd: gradient wrt q, k, v of a scalar readout
        step = jax.jit(jax.grad(
            lambda q, k, v: fn(q, k, v, causal=True)
            .astype(jnp.float32).sum(), argnums=(0, 1, 2)))
    try:
        for _ in range(WARMUP):
            out = step(q, k, v)
        _force(out)
        t0 = time.perf_counter()
        for _ in range(N_CALLS):
            out = step(q, k, v)
        _force(out)
        return (time.perf_counter() - t0) / N_CALLS * 1000.0
    except Exception as e:  # OOM at compile/run, kernel unsupported, ...
        return f"{type(e).__name__}"


def impl_sweep(args):
    backend = jax.default_backend()
    rows = []
    for t in args.ts:
        for mode in ("fwd", "fwd+bwd"):
            for impl in ("reference", "blockwise", "flash"):
                # full materialization at T>=8192 is pointless (and the
                # [B,H,T,T] matrix alone is >= 4 GB): skip, like the judge
                if impl == "reference" and t > 4096:
                    rows.append((t, mode, impl, "skipped"))
                    continue
                ms = measure(impl, mode, t)
                rows.append((t, mode, impl, ms))
                if args.json:
                    print(json.dumps({
                        "bench": "attention", "backend": backend,
                        "B": B, "H": H, "D": D, "T": t, "mode": mode,
                        "impl": impl,
                        "ms": ms if isinstance(ms, float) else None,
                        "error": None if isinstance(ms, float) else ms,
                    }), flush=True)

    print(f"\nbackend={backend}  B{B}/H{H}/D{D} bf16 causal  "
          f"(N={N_CALLS} queue-timed, value-forced sync)\n")
    print(f"{'T':>6} {'mode':>8} | {'reference':>12} {'blockwise':>12} "
          f"{'flash':>12}")
    by_key = {(t, m, i): v for t, m, i, v in rows}
    for t in args.ts:
        for mode in ("fwd", "fwd+bwd"):
            cells = []
            for impl in ("reference", "blockwise", "flash"):
                v = by_key[(t, mode, impl)]
                cells.append(f"{v:>10.1f}ms" if isinstance(v, float)
                             else f"{v:>12}")
            print(f"{t:>6} {mode:>8} | " + " ".join(cells))


# --------------------------------------------------------------------------
# kernel-registry A/B (--kernels)
# --------------------------------------------------------------------------

def _time_step(step, inputs, n):
    out = None
    for _ in range(WARMUP):
        out = step(*inputs)
    _force(out)
    t0 = time.perf_counter()
    for _ in range(n):
        out = step(*inputs)
    _force(out)
    return (time.perf_counter() - t0) / n * 1000.0


def _max_abs(a, b):
    return float(np.max(np.abs(np.asarray(a, np.float32)
                               - np.asarray(b, np.float32))))


def kernel_prefill_ab(args):
    """Tuned flash (registry build) vs the stock XLA reference, fwd and
    fwd+bwd, per sequence length."""
    from deeplearning4j_tpu import kernels
    from deeplearning4j_tpu.kernels.registry import AttentionEnvelope

    k = kernels.REGISTRY.get("flash_attention")
    rows = []
    for t in args.kts:
        env = AttentionEnvelope(b=args.kb, h=args.kh, tq=t, tk=t,
                                d=args.kd, dtype="float32",
                                backend=kernels.backend(), causal=True,
                                masked=False)
        if not k.supports(env):
            continue
        res = kernels.autotune(k, env, max_candidates=args.candidates,
                               trials=1)
        inputs = k.make_inputs(env, seed=0)
        flash_fn = jax.jit(k.build(env, res.tiling))
        stock_fn = jax.jit(k.reference(env))
        parity = _max_abs(flash_fn(*inputs), stock_fn(*inputs))

        def loss(fn):
            return jax.jit(jax.grad(
                lambda q, kk, v: jnp.sum(fn(q, kk, v) ** 2),
                argnums=(0, 1, 2)))

        g_par = max(_max_abs(a, b) for a, b in
                    zip(loss(k.build(env, res.tiling))(*inputs),
                        loss(k.reference(env))(*inputs)))
        row = {
            "t": t, "tiling": list(res.tiling),
            "flash_ms": round(_time_step(flash_fn, inputs, args.kn), 3),
            "stock_ms": round(_time_step(stock_fn, inputs, args.kn), 3),
            "flash_bwd_ms": round(_time_step(
                loss(k.build(env, res.tiling)), inputs, args.kn), 3),
            "stock_bwd_ms": round(_time_step(
                loss(k.reference(env)), inputs, args.kn), 3),
            "fwd_max_abs_err": parity,
            "bwd_max_abs_err": g_par,
        }
        rows.append(row)
        print(f"prefill t={t}: flash {row['flash_ms']}ms vs stock "
              f"{row['stock_ms']}ms (bwd {row['flash_bwd_ms']} vs "
              f"{row['stock_bwd_ms']}), |err| fwd {parity:.2e} "
              f"bwd {g_par:.2e}, tiling {res.tiling}")
    return rows


def kernel_paged_ab(args):
    """Paged gather vs the masked full-cache read, per occupancy: every
    row's positions sit at the given fraction of the cache bucket, so
    the paged kernel touches ceil(occ * tk / page) pages while the
    masked read always streams the whole bucket."""
    from deeplearning4j_tpu import kernels
    from deeplearning4j_tpu.kernels.registry import AttentionEnvelope

    k = kernels.REGISTRY.get("paged_decode_attention")
    tk = args.ktk
    env = AttentionEnvelope(b=args.kb, h=args.kh, tq=1, tk=tk, d=args.kd,
                            dtype="float32", backend=kernels.backend(),
                            causal=True, masked=False)
    if not k.supports(env):
        return []
    res = kernels.autotune(k, env, max_candidates=args.candidates,
                           trials=1)
    q, kc, vc, _ = k.make_inputs(env, seed=0)
    paged_fn = jax.jit(k.build(env, res.tiling))
    stock_fn = jax.jit(k.reference(env))
    rows = []
    for occ in args.occupancies:
        pos = jnp.full((args.kb,), max(0, int(occ * tk) - 1), jnp.int32)
        parity = _max_abs(paged_fn(q, kc, vc, pos),
                          stock_fn(q, kc, vc, pos))
        row = {
            "tk": tk, "occupancy": occ, "page": int(res.tiling[0]),
            "paged_ms": round(_time_step(
                paged_fn, (q, kc, vc, pos), args.kn), 3),
            "masked_ms": round(_time_step(
                stock_fn, (q, kc, vc, pos), args.kn), 3),
            "max_abs_err": parity,
        }
        rows.append(row)
        print(f"decode tk={tk} occ={occ}: paged {row['paged_ms']}ms "
              f"(page {row['page']}) vs masked {row['masked_ms']}ms, "
              f"|err| {parity:.2e}")
    return rows


def kernel_engine_leg(args):
    """End-to-end: stock vs use_kernels decoder, greedy token identity
    + zero recompiles after warmup + kern: tokens in the step keys."""
    from deeplearning4j_tpu import kernels
    from deeplearning4j_tpu.optimize import aot_cache
    from deeplearning4j_tpu.zoo.graphs import TransformerEncoder

    margs = dict(vocab_size=32, embed_dim=16, n_heads=2, n_layers=2,
                 max_len=32, causal=True, lm_head=True, seed=7)
    dargs = dict(max_batch=2, kv_bucket_min=16, prompt_bucket_min=8)
    stock = TransformerEncoder(**margs).decoder(**dargs)
    kern = TransformerEncoder(use_kernels=True, **margs).decoder(**dargs)
    t0 = time.monotonic()
    tuned = kernels.autotune_decoder(kern, max_candidates=args.candidates,
                                     trials=1)
    tune_s = time.monotonic() - t0
    tag = kern._ktag()
    stock.warm_all(fused_steps=(1, 2))
    kern.warm_all(fused_steps=(1, 2))
    prompts = [[3, 1, 4, 1, 5], [2, 7, 1], [9] * 12]
    m0 = aot_cache.stats()["misses"]
    identical = True
    for p in prompts:
        identical = identical and (stock.generate(p, 10)
                                   == kern.generate(p, 10))
    leg = {
        "greedy_identical_to_stock": identical,
        "recompiles_after_warmup": aot_cache.stats()["misses"] - m0,
        "tuned_envelopes": len(tuned),
        "autotune_seconds": round(tune_s, 2),
        "flash_token_in_keys": "kern:flash_attention:" in tag,
        "paged_token_in_keys": "kern:paged_decode_attention:" in tag,
    }
    print(f"engine: identical={identical}, "
          f"recompiles={leg['recompiles_after_warmup']}, "
          f"{leg['tuned_envelopes']} envelopes tuned in {tune_s:.1f}s")
    return leg


def kernel_ab(args):
    from deeplearning4j_tpu import kernels

    backend = jax.default_backend()
    results = {
        "bench": "attention_kernels_r01",
        "mode": "cpu-interpret" if kernels.backend() != "tpu" else "tpu",
        "caveat": ("CPU proxy: kernel bodies run through the Pallas "
                   "interpreter, so ms columns rank the interpreter, "
                   "not the MXU. The committed record is parity + "
                   "token identity + zero recompiles + the winner "
                   "set; the speed claim needs --tpu on a real chip."),
        "backend": backend,
        "shape": {"b": args.kb, "h": args.kh, "d": args.kd},
        "prefill_flash_vs_stock": kernel_prefill_ab(args),
        "decode_paged_vs_masked": kernel_paged_ab(args),
        "engine": kernel_engine_leg(args),
    }
    print(json.dumps(results, indent=2))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(results, f, indent=2)
        print(f"wrote {args.out}")
    if args.smoke:
        eng = results["engine"]
        assert eng["greedy_identical_to_stock"], \
            "use_kernels greedy output != stock decoder"
        assert eng["recompiles_after_warmup"] == 0, \
            f"{eng['recompiles_after_warmup']} recompiles after warmup"
        assert eng["flash_token_in_keys"] and eng["paged_token_in_keys"]
        for row in results["prefill_flash_vs_stock"]:
            assert row["fwd_max_abs_err"] < 1e-4, row
            assert row["bwd_max_abs_err"] < 1e-3, row
        for row in results["decode_paged_vs_masked"]:
            assert row["max_abs_err"] < 1e-4, row
        print("attention-smoke OK: parity pinned, token-identical, "
              "0 recompiles")
    return 0


def main():
    global N_CALLS
    ap = argparse.ArgumentParser()
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--n", type=int, default=N_CALLS,
                    help="queued calls per impl-sweep measurement")
    ap.add_argument("--ts", type=int, nargs="*",
                    default=[1024, 2048, 4096, 8192, 16384])
    ap.add_argument("--kernels", action="store_true",
                    help="run the kernel-registry A/B harness instead "
                         "of the impl sweep")
    ap.add_argument("--kts", type=int, nargs="*", default=[64, 128, 256],
                    help="sequence lengths for the flash A/B leg")
    ap.add_argument("--ktk", type=int, default=256,
                    help="cache bucket for the paged A/B leg")
    ap.add_argument("--occupancies", type=float, nargs="*",
                    default=[0.25, 0.5, 1.0])
    ap.add_argument("--kb", type=int, default=2)
    ap.add_argument("--kh", type=int, default=4)
    ap.add_argument("--kd", type=int, default=16)
    ap.add_argument("--kn", type=int, default=3,
                    help="timed calls per kernel-leg measurement")
    ap.add_argument("--candidates", type=int, default=4,
                    help="autotune candidates per envelope")
    ap.add_argument("--out", default=None,
                    help="write the kernel A/B JSON blob here")
    ap.add_argument("--tpu", action="store_true",
                    help="run on the real chip instead of the CPU proxy")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny axes + assertions (make attention-smoke)")
    args = ap.parse_args()
    N_CALLS = args.n
    if not args.tpu:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if args.smoke:
        args.kernels = True
        args.kts = [16, 32]
        args.ktk = 32
        args.occupancies = [0.5, 1.0]
        args.kn = 2
        args.candidates = 2
    if args.kernels:
        return kernel_ab(args)
    impl_sweep(args)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
