"""ResNet-50 training-step breakdown on the real chip (the measured
basis for BASELINE.md's MFU analysis — re-run this script to regenerate).

Decomposes the batch-256 bf16 train step into:
- full step (fwd + bwd + Adam, donated buffers, dependent-chain sync);
- forward-only loss and value_and_grad (updater cost by subtraction);
- per-PREFIX forward and forward+backward costs at each stage boundary
  (stem, res2..res5, head) — the per-stage cost is the difference of
  consecutive prefixes, so transposed-bwd-conv costs land in the stage
  that owns them.

Protocol: every closure is jitted; each measurement queues N identical
calls then forces ONE value (``block_until_ready`` returns at dispatch
on the axon tunnel, so a value read is the only real sync), min of 3
reps, the measured null round-trip subtracted once per rep. Queuing
identical calls is safe here because the inputs are the same arrays
every call (the round-1 OOM-stall came from chained UN-donated train
steps holding N params trees alive). Backward closures return a scalar
REDUCED FROM THE GRADS — returning only the loss value lets XLA
dead-code-eliminate the whole backward pass (the first version of this
script did exactly that and measured fwd+bwd == fwd).
"""

import argparse
import dataclasses
import json
import time

import numpy as np

from deeplearning4j_tpu.telemetry import PHASES

PHASE_INGEST, PHASE_COMPUTE, PHASE_GRAD_SYNC, PHASE_HOST_GAP = PHASES

# --phases / --fused-steps output rows, keyed off the framework's
# canonical phase names (deeplearning4j_tpu.telemetry.PHASES) so the
# bench breakdown and the telemetry spans cannot drift apart — pinned by
# tests/test_telemetry.py
PHASE_ROWS = {
    PHASE_INGEST: (f"{PHASE_INGEST}_h2d", f"{PHASE_INGEST}_after_overlap"),
    PHASE_COMPUTE: ("step_cached_fit", "step_streaming", "step_ring"),
    PHASE_GRAD_SYNC: (PHASE_GRAD_SYNC,),
    PHASE_HOST_GAP: (f"{PHASE_HOST_GAP}_per_step_k1",
                     f"{PHASE_HOST_GAP}_per_step_fused"),
}

BATCH = 256
IMG = 224
CLASSES = 1000
N = 6

BOUNDARIES = ["stem_bn", "stem_pool", "res2c_relu", "res3d_relu",
              "res4f_relu", "res5c_relu", "avgpool"]


def _sync(x):
    import jax.numpy as jnp

    return float(jnp.asarray(x).astype(jnp.float32).reshape(-1)[0])


_RT_MS = [0.0]  # measured enqueue+value-sync round-trip, subtracted per rep


def timed(fn, *args, n=N, reps=3):
    out = fn(*args)
    _sync(out)  # compile + settle
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        for _ in range(n):
            out = fn(*args)
        _sync(out)
        ms = ((time.perf_counter() - t0) * 1000.0 - _RT_MS[0]) / n
        best = min(best, ms)
    return best


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=BATCH)
    ap.add_argument("--img", type=int, default=IMG,
                    help="input resolution (default 224; shrink for "
                         "CPU-proxy runs of --fused-steps/--phases)")
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--s2d", action="store_true",
                    help="exact space-to-depth stem rewrite (MLPerf trick)")
    ap.add_argument("--quick", action="store_true",
                    help="only train_step / fwd / fwd+bwd (skip prefixes)")
    ap.add_argument("--phases", action="store_true",
                    help="per-phase step breakdown (ingest / compute / "
                         "sync overlap) instead of the prefix sweep")
    ap.add_argument("--fused-steps", type=int, default=0,
                    help="K-step fused A/B: train the same batch stream "
                         "through the per-step path (K=1) and the fused "
                         "lax.scan driver (fused_steps=K), reporting the "
                         "telemetry-measured host gap per step, img/s, "
                         "recompiles after the first super-step, and the "
                         "K=1 vs K final-params max |delta| (0.0 = "
                         "bit-identical; conv bodies may show ulp-level "
                         "compilation variance — docs/observability.md)")
    ap.add_argument("--health", action="store_true",
                    help="enable the in-graph health guards (WARN policy) "
                         "so train_step / --phases rows measure the "
                         "guarded step — compare against a run without "
                         "the flag for the guard overhead (<5%% target)")
    ap.add_argument("--kernels", action="store_true",
                    help="Pallas kernel-registry A/B: autotune every "
                         "routable envelope at this batch, then train "
                         "fresh nets through stock XLA and the "
                         "use_kernels path, reporting img/s per mode, "
                         "recompiles after warmup (must be 0), and the "
                         "final-params max |delta|. Off-TPU the kernels "
                         "run via the Pallas interpreter — correctness "
                         "proxy only, not a speed measurement "
                         "(docs/kernels.md)")
    args = ap.parse_args()
    batch = args.batch
    img = int(args.img)

    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.conf.updaters import Adam
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.zoo.graphs import ResNet50

    if args.health:
        from deeplearning4j_tpu.telemetry import health

        health.configure(policy=health.AnomalyPolicy.WARN)

    model = ResNet50(num_classes=CLASSES, height=img, width=img,
                     updater=Adam(learning_rate=1e-3))
    model.stem_space_to_depth = bool(args.s2d)
    cfg = dataclasses.replace(model.conf(), compute_dtype="bfloat16")
    net = ComputationGraph(cfg).init()

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.integers(0, 256, (batch, img, img, 3),
                                 dtype=np.uint8))
    y = jnp.asarray(np.eye(CLASSES, dtype=np.float32)[
        rng.integers(0, CLASSES, batch)])
    lmask = jnp.ones((batch,), jnp.float32)

    # null round-trip: queue 10 trivial calls + one value sync; the total
    # IS the round-trip (per-call compute ~0)
    null = jax.jit(lambda v: v + 1.0)
    _sync(null(jnp.float32(0.0)))
    rts = []
    for _ in range(5):
        t0 = time.perf_counter()
        out = jnp.float32(0.0)
        for _ in range(10):
            out = null(out)
        _sync(out)
        rts.append((time.perf_counter() - t0) * 1000.0)
    _RT_MS[0] = min(rts)
    rows = {"null_roundtrip": _RT_MS[0]}

    # ---- Pallas kernel-registry A/B (ROADMAP item 5) ---------------------
    if args.kernels:
        from deeplearning4j_tpu import kernels as kern
        from deeplearning4j_tpu.datasets.dataset import DataSet as _DS
        from deeplearning4j_tpu.optimize import aot_cache

        n_steps = 6
        cfg_on = dataclasses.replace(cfg, use_kernels=True)
        tuned = kern.autotune_model(cfg_on, batch, max_candidates=8)
        rows["kernels_tuned_envelopes"] = len(tuned)
        print(f"# kernels backend={kern.capability()} "
              f"tuned={len(tuned)} envelopes")

        def run(cfgx, label):
            netx = ComputationGraph(cfgx).init()  # fresh net per mode
            ds = _DS(np.asarray(x), np.asarray(y))
            netx.fit_batch(ds)  # compile + settle
            netx.fit_batch(ds)
            miss0 = aot_cache.stats()["misses"]
            t0 = time.perf_counter()
            for _ in range(n_steps):
                netx._fit_batch_async(ds)
            _ = float(netx.score_value)
            wall = time.perf_counter() - t0
            rows[f"imgs_per_sec_{label}"] = n_steps * batch / wall
            rows[f"recompiles_after_warmup_{label}"] = (
                aot_cache.stats()["misses"] - miss0)
            return netx

        net_a = run(cfg, "xla")
        net_b = run(cfg_on, "kernels")
        rows["kernels_params_max_delta"] = max(
            float(jnp.max(jnp.abs(jnp.asarray(a, jnp.float32)
                                  - jnp.asarray(b, jnp.float32))))
            for a, b in zip(jax.tree_util.tree_leaves(net_a.params),
                            jax.tree_util.tree_leaves(net_b.params)))
        assert rows["recompiles_after_warmup_xla"] == 0
        assert rows["recompiles_after_warmup_kernels"] == 0
        if args.json:
            print(json.dumps({kk: round(v, 4) for kk, v in rows.items()}))
            return
        print(f"\nResNet-50 batch {batch} kernel-registry A/B "
              f"({n_steps} steps/mode)\n")
        for kk, v in rows.items():
            print(f"{kk:>32} {v:>10.4f}")
        return

    # ---- K-step fused A/B (round 11): host gap per step, before/after ----
    if args.fused_steps:
        from deeplearning4j_tpu import telemetry
        from deeplearning4j_tpu.datasets.dataset import DataSet as _DS
        from deeplearning4j_tpu.datasets.iterators import (
            ListDataSetIterator,
        )
        from deeplearning4j_tpu.optimize import aot_cache

        k = int(args.fused_steps)
        n_super = 4
        n_steps = k * n_super
        rngf = np.random.default_rng(11)
        base = [(rngf.integers(0, 256, (batch, img, img, 3),
                               dtype=np.uint8),
                 np.eye(CLASSES, dtype=np.float32)[
                     rngf.integers(0, CLASSES, batch)])
                for _ in range(n_steps)]

        def stream():
            # fresh numpy copies per run: write_back migrates arrays to
            # device, and both modes must stage the same host stream
            return ListDataSetIterator(
                [_DS(np.array(f), np.array(l)) for f, l in base])

        def run(kk, label):
            netx = ComputationGraph(cfg).init()
            netx.fit(stream(), epochs=1, fused_steps=kk)  # compile+settle
            miss0 = aot_cache.stats()["misses"]
            # throughput epoch: fully async pipeline, telemetry off
            t0 = time.perf_counter()
            netx.fit(stream(), epochs=1, fused_steps=kk)
            jax.block_until_ready(netx.params)
            wall = time.perf_counter() - t0
            rows[f"imgs_per_sec_{label}"] = n_steps * batch / wall
            rows[f"recompiles_after_warmup_{label}"] = (
                aot_cache.stats()["misses"] - miss0)
            # host-gap epoch: sync-mode spans block on each dispatch's
            # device result, so the gap between spans is PURE host
            # dispatch-loop work (no device overlap / thread starvation)
            telemetry.reset()
            telemetry.enable(sync=True)
            netx.fit(stream(), epochs=1, fused_steps=kk)
            jax.block_until_ready(netx.params)
            telemetry.disable()
            evs = [e for e in telemetry.events()
                   if e["name"] == PHASE_HOST_GAP]
            gap_ms = sum(e["duration_ns"] for e in evs) / 1e6
            gsteps = sum(e.get("attrs", {}).get("steps", 1) for e in evs)
            rows[f"{PHASE_HOST_GAP}_per_step_{label}"] = (
                gap_ms / max(gsteps, 1))
            return netx

        net1 = run(1, "k1")
        netk = run(k, "fused")
        # the acceptance invariant: K=1 and K=K train IDENTICALLY on the
        # same stream (max |param delta| 0.0 = bit-identical)
        rows["fused_params_max_delta"] = max(
            float(jnp.max(jnp.abs(jnp.asarray(a, jnp.float32)
                                  - jnp.asarray(b, jnp.float32))))
            for a, b in zip(jax.tree_util.tree_leaves(net1.params),
                            jax.tree_util.tree_leaves(netk.params)))
        if args.json:
            print(json.dumps({kk: round(v, 4) for kk, v in rows.items()}))
            return
        print(f"\nResNet-50 batch {batch} fused-{k} A/B "
              f"({n_steps} steps, {n_super} super-steps)\n")
        for kk, v in rows.items():
            print(f"{kk:>32} {v:>10.4f}")
        return

    params, state = net.params, net.state

    # ---- full production step (donated, dependent chain via fit path) ----
    from deeplearning4j_tpu.datasets.dataset import DataSet

    ds = DataSet(np.asarray(x), np.asarray(y))
    net.fit_batch(ds)  # compile + settle
    net.fit_batch(ds)
    t0 = time.perf_counter()
    for _ in range(N):
        net.fit_batch(ds)  # fit_batch syncs (float(loss)) per call
    rows["train_step"] = ((time.perf_counter() - t0) * 1000.0
                          - N * _RT_MS[0]) / N
    params, state = net.params, net.state  # post-donation trees

    # ---- per-phase breakdown: ingest / compute / sync-after-overlap ----
    # (round 6 — the denominator for the "<3% of step time in gradient
    # sync + ingest" criterion; on one chip gradient sync is 0 and the
    # ingest share is whatever the double-buffered ring fails to hide)
    if args.phases:
        from deeplearning4j_tpu.datasets.dataset import DataSet as _DS
        from deeplearning4j_tpu.datasets.iterators import (
            ListDataSetIterator,
        )
        from deeplearning4j_tpu.datasets.prefetch import DeviceRingIterator

        rng2 = np.random.default_rng(7)
        n_stream = 6
        fresh = [
            _DS(rng2.integers(0, 256, (batch, img, img, 3),
                              dtype=np.uint8),
                np.eye(CLASSES, dtype=np.float32)[
                    rng2.integers(0, CLASSES, batch)])
            for _ in range(n_stream)]

        # raw host->device transfer cost of one uint8 batch (fresh buffer
        # per rep so no caching), value-synced
        ing = []
        for ds_f in fresh[:3]:
            t0 = time.perf_counter()
            dev = jax.device_put(np.asarray(ds_f.features))
            _sync(dev[0, 0, 0, :1])
            ing.append((time.perf_counter() - t0) * 1000.0 - _RT_MS[0])
        rows[f"{PHASE_INGEST}_h2d"] = min(ing)

        def stream_ms(iterator):
            t0 = time.perf_counter()
            net.fit(iterator, epochs=1)
            _ = net.score_value  # sync
            return ((time.perf_counter() - t0) * 1000.0) / n_stream

        # compute baseline through the SAME fit loop, batches already
        # device-resident (write_back migrated them on a priming epoch) —
        # so the streaming/ring deltas isolate INGEST, not fit-loop host
        # overhead vs a bare-jit dispatch
        cached = ListDataSetIterator(fresh)
        net.fit(cached, epochs=1)  # priming epoch: migrate + settle
        rows["step_cached_fit"] = stream_ms(cached)
        # sequential streaming: transfer serialized with the step
        rows["step_streaming"] = stream_ms(ListDataSetIterator([
            _DS(np.array(d.features), np.array(d.labels))
            for d in fresh]))
        # double-buffered ring: batch N+1's device_put overlaps step N
        rows["step_ring"] = stream_ms(DeviceRingIterator(
            ListDataSetIterator([
                _DS(np.array(d.features), np.array(d.labels))
                for d in fresh]), depth=2, donate=True))

        comp = rows["step_cached_fit"]
        ring = rows["step_ring"]
        rows[f"{PHASE_INGEST}_after_overlap"] = max(0.0, ring - comp)
        rows[PHASE_GRAD_SYNC] = 0.0  # single chip: no DP collective
        denom = max(ring, comp)
        rows["sync_plus_ingest_pct_of_step"] = round(
            100.0 * (rows[PHASE_GRAD_SYNC]
                     + rows[f"{PHASE_INGEST}_after_overlap"])
            / denom, 2)

    if args.phases:
        if args.json:
            print(json.dumps({k: round(v, 2) for k, v in rows.items()}))
            return
        print(f"\nResNet-50 batch {batch} per-PHASE breakdown (ms)\n")
        for k, v in rows.items():
            print(f"{k:>28} {v:>9.2f}")
        print("\nstep share of (grad sync + unhidden ingest): "
              f"{rows['sync_plus_ingest_pct_of_step']:.2f}%")
        return

    # ---- forward-only loss + value_and_grad ----
    def loss_fn(p, feats):
        loss, _ = net._loss(p, state, (feats,), (y,), (None,), (lmask,),
                            rng=None, train=True)
        return loss

    def grad_scalar(vg_out):
        # depend on EVERY grad leaf or XLA DCEs the backward pass
        v, g = vg_out
        return v + sum(jnp.sum(l.astype(jnp.float32))
                       for l in jax.tree_util.tree_leaves(g))

    fwd = jax.jit(loss_fn)
    rows["forward_loss"] = timed(fwd, params, x)

    vg = jax.jit(lambda p, f: grad_scalar(jax.value_and_grad(loss_fn)(p, f)))
    rows["forward_backward"] = timed(vg, params, x)

    # ---- per-prefix forward / forward+backward ----
    def prefix_fn(boundary):
        keep = set()
        for name in net._topo:
            keep.add(name)
            if name == boundary:
                break
        skip = set(net._topo) - keep

        def run(p, feats):
            feats = net._dequant(feats, 0)
            fp, (feats,) = net._fwd_cast(p, (feats,))
            acts, _, _ = net._forward(fp, state, (feats,), train=True,
                                      rng=None, skip=skip)
            return acts[boundary].astype(jnp.float32).sum()

        return run

    for b in ([] if args.quick else BOUNDARIES):
        f = prefix_fn(b)
        rows[f"fwd_to_{b}"] = timed(jax.jit(f), params, x)
        g = jax.jit(lambda p, feats, _f=f: grad_scalar(
            jax.value_and_grad(_f)(p, feats)))
        rows[f"fwdbwd_to_{b}"] = timed(g, params, x)

    if args.json:
        print(json.dumps({k: round(v, 2) for k, v in rows.items()}))
        return

    print(f"\nResNet-50 batch {batch} bf16 breakdown (ms; round-trip "
          f"{_RT_MS[0]:.1f}ms subtracted; {N} queued calls/sync, min of "
          f"3 reps)\n")
    print(f"{'probe':>22} {'ms':>9}")
    for k, v in rows.items():
        print(f"{k:>22} {v:>9.1f}")
    if not args.quick:
        print("\nper-stage deltas (prefix differences):")
        prev_f = prev_b = 0.0
        for b in BOUNDARIES:
            fv, bv = rows[f"fwd_to_{b}"], rows[f"fwdbwd_to_{b}"]
            print(f"{b:>22} fwd {fv - prev_f:>7.1f}  "
                  f"fwd+bwd {bv - prev_b:>7.1f}")
            prev_f, prev_b = fv, bv
    upd = rows["train_step"] - rows["forward_backward"]
    print(f"\nupdater+overheads (train_step - fwd_bwd): {upd:.1f} ms")


if __name__ == "__main__":
    main()
