# Developer/CI targets. The tier-1 suite command of record lives in
# ROADMAP.md; these are the quick subsets.

PY ?= python

.PHONY: telemetry-smoke
# Telemetry-layer smoke: span/registry/export tests + the check that
# bench_resnet_profile.py --phases keys match telemetry phase names.
telemetry-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests -q -m telemetry \
		-p no:cacheprovider

.PHONY: health-smoke
# Health-layer smoke: guard-vector math, anomaly policies
# (WARN/SKIP_STEP/ROLLBACK/HALT), and the induced-NaN e2e that must HALT
# cleanly and leave a flight-recorder crash bundle behind.
health-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests -q -m health \
		-p no:cacheprovider

.PHONY: serve-smoke
# Serving smoke: the dynamic-batcher test subset, then a live HTTP
# round-trip (start InferenceServer -> concurrent ragged /predict ->
# scrape /metrics -> clean stop, asserting zero recompiles after warmup).
serve-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests -q -m serving \
		-p no:cacheprovider
	$(PY) bench_serving.py --smoke

.PHONY: chaos-smoke
# Chaos smoke: the deterministic fault-plan suite (seeded injections,
# retry/backoff math, breaker trip->half-open->close, crash-mid-write
# checkpointing, bit-identical TrainingSession resume) on CPU with the
# same pinning as tier-1. Every fault is armed with a fixed seed, so a
# failure here replays exactly.
chaos-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests -q -m resilience \
		-p no:cacheprovider

.PHONY: fused-smoke
# Fused multi-step driver smoke: K=1 vs K=4 bit-identity (params, updater
# state, listener losses), super-step health granularity, K-keyed AOT
# cache, kill-and-resume under fused_steps. CPU-pinned, fixed seeds.
fused-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests -q -m fused \
		-p no:cacheprovider

.PHONY: shard-smoke
# Sharding smoke: rule-table resolution, ZeRO-vs-all-reduce bit
# identity on the simulated 8-device mesh, save-on-mesh-A /
# restore-on-mesh-B, collective-counter parity. CPU-pinned with the
# same virtual-device flag as tier-1.
shard-smoke:
	JAX_PLATFORMS=cpu \
	XLA_FLAGS="--xla_force_host_platform_device_count=8" \
	$(PY) -m pytest tests -q -m sharding -p no:cacheprovider

.PHONY: decode-smoke
# Continuous-batching generation smoke: KV-cache math vs the no-cache
# oracle, continuous-vs-sequential token identity, late-join/EOS-retire
# scheduling, breaker/deadline admission, zero recompiles after warmup —
# then the closed-loop token-throughput bench in smoke mode (continuous
# must beat sequential on aggregate tokens/s; prefix-cache leg must hit
# the trie, speculative leg uses an oracle draft so acceptance and
# identity assert without a training run).
decode-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests -q -m decode \
		-p no:cacheprovider
	JAX_PLATFORMS=cpu $(PY) bench_decode.py --smoke \
		--prefix-cache --speculative

.PHONY: comms-smoke
# Collective-scheduler smoke: plan determinism/digests, scheduler-vs-
# legacy bit-identity for every wrapper exchange mode, PRG205 plan
# audit, cross-mesh reshard + publish_to_engine — then the legacy-vs-
# scheduler A/B bench asserting no regression in collective launches or
# bytes. CPU-pinned, 8 virtual devices, fixed seeds.
comms-smoke:
	JAX_PLATFORMS=cpu \
	XLA_FLAGS="--xla_force_host_platform_device_count=8" \
	$(PY) -m pytest tests -q -m comms -p no:cacheprovider
	$(PY) bench_collectives.py --smoke

.PHONY: platform-smoke
# Multi-tenant platform smoke: the registry/hot-swap/canary/quota test
# subset (seeded chaos, deterministic rollback), then the two-tenant
# faulted-canary bench in assert mode — the healthy tenant's responses
# must stay byte-identical with zero recompiles while the canary trips,
# sheds, and rolls back.
platform-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests -q -m platform \
		-p no:cacheprovider
	JAX_PLATFORMS=cpu $(PY) bench_serving.py --multi-model --seconds 1.5 \
		--assert-isolation --out /tmp/bench_serving_mt_smoke.json

.PHONY: pod-smoke
# Pod scale-out smoke: the distributed-snapshot / pod-preemption test
# subset — seeded host-death chaos with bit-identical resume, the
# mid-shard-write commit-protocol pins, cross-pod-shape restore through
# comms.reshard, and the make_array scatter/gather parity pins. The
# real 2-process leg probes the jaxlib for CPU multi-process
# collectives and skips cleanly where they are absent.
pod-smoke:
	JAX_PLATFORMS=cpu \
	XLA_FLAGS="--xla_force_host_platform_device_count=8" \
	$(PY) -m pytest tests -q -m pod -p no:cacheprovider

.PHONY: kernels-smoke
# Pallas kernel-subsystem smoke: registry parity against the XLA
# references (interpret mode), autotuner + digest-verified tuning
# cache (corruption refusal, cross-process persistence), off-by-default
# bitwise pin, fallback zero-recompile churn, PRG207 + donation audit
# on kernel-bearing steps — then the in-process A/B bench asserting
# parity and zero recompiles after warmup for both modes.
kernels-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests -q -m kernels \
		-p no:cacheprovider
	JAX_PLATFORMS=cpu $(PY) bench_conv_matrix.py --kernels --smoke

.PHONY: attention-smoke
# Attention-kernel smoke: flash/paged parity (per candidate, f32+bf16),
# flash gradient parity, routing/fallback/retune pins, the kernel-routed
# decode subset (continuous-vs-sequential token identity, prefix-attached
# pages, donation audit) — then the kernel-registry A/B bench in smoke
# mode (flash-vs-stock prefill, paged-vs-masked decode across
# occupancies, zero recompiles after warmup asserted).
attention-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_kernels.py -q \
		-k "flash or paged or attention or attn or cache_tag" \
		-p no:cacheprovider
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_decode.py -q -k kern \
		-p no:cacheprovider
	JAX_PLATFORMS=cpu $(PY) bench_attention.py --smoke

.PHONY: obs-smoke
# Observability smoke: the request-tracing / SLO burn-rate test subset
# (traceparent round-trip over live HTTP, one trace across prefix-attach
# → join → decode windows → retire, replay-deterministic tail sampling
# and SLO transitions, flight-recorder trace capture + keep-last-N,
# /traces + /slo endpoints, SRC107 fixtures), then the tracing-overhead
# A/B bench in both serving and decode shapes — tracing-on must hold
# the pinned throughput budget with zero recompiles in BOTH modes.
obs-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests -q -m obs -p no:cacheprovider
	JAX_PLATFORMS=cpu $(PY) bench_serving.py --traces --seconds 1.5 \
		--rounds 2 --out /tmp/bench_serving_traces_smoke.json
	JAX_PLATFORMS=cpu $(PY) bench_decode.py --traces --smoke \
		--out /tmp/bench_decode_traces_smoke.json

.PHONY: lint
# Repo-discipline source lint (analysis/source.py AST rules): host syncs
# in compiled functions, lock discipline on shared registries, wall-clock/
# RNG in traced code, fit-loop bracketing, unused imports. Exits nonzero
# on any unwaived finding >= WARN; waive inline with
# "# dl4j: waive SRC1xx — reason" (docs/analysis.md has the catalog).
lint:
	JAX_PLATFORMS=cpu $(PY) -m deeplearning4j_tpu.analysis source

.PHONY: analysis-smoke
# Program-lint smoke: the per-rule seeded-defect fixtures, then the
# compile-time pass for real — one MLN / graph / ZeRO-wrapper step each
# through the AOT cache with the lint hook armed (donation audit included).
# CPU-pinned, 2 virtual devices, fixed seeds.
analysis-smoke:
	JAX_PLATFORMS=cpu \
	XLA_FLAGS="--xla_force_host_platform_device_count=2" \
	$(PY) -m pytest tests -q -m analysis -p no:cacheprovider
	JAX_PLATFORMS=cpu \
	XLA_FLAGS="--xla_force_host_platform_device_count=2" \
	$(PY) -m deeplearning4j_tpu.analysis program

.PHONY: bench-serving
# Closed-loop 8-client serving benchmark: locked single-request baseline
# vs the dynamic micro-batching engine (acceptance bar: >= 4x).
bench-serving:
	$(PY) bench_serving.py --assert-speedup 4

.PHONY: quant-smoke
# Quantized-serving smoke: the int8 calibration / kernel-parity /
# registry / accuracy-gate test subset, then the f32-vs-int8 platform
# A/B (calibrate -> quantize -> canary behind the accuracy arm ->
# promote), asserting zero recompiles after warmup in BOTH modes and a
# bounded accuracy_max_delta.
quant-smoke:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests -q -m quant \
		-p no:cacheprovider
	$(PY) bench_serving.py --quant --seconds 1.5 --rounds 1 \
		--hidden 96 --out /tmp/bench_serving_quant_smoke.json

.PHONY: tier1
tier1:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m 'not slow' \
		--continue-on-collection-errors -p no:cacheprovider
